"""Engine-side durable streams: /v1/resume replay adoption and graceful
drain (SIGTERM / POST /api/drain) — docs/resilience.md, docs/deployment.md.

Drain is one-way (the process is expected to exit or restart), so every
drain test builds its own engine.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine


def _build_engine(slot_capacity: int = 128, **extra) -> Engine:
    return Engine.from_preset(
        "debug-tiny", num_slots=4, slot_capacity=slot_capacity,
        prefill_buckets=(16, 32), seed=0, kv_page_size=16, **extra,
    )


async def _client(engine) -> TestClient:
    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    return client


def _chat_body(engine, *, stream=True, max_tokens=12, temperature=0.0,
               seed=None, replay=True):
    body = {
        "model": engine.model_id,
        "messages": [{"role": "user", "content": "the quick brown fox"}],
        "max_tokens": max_tokens, "temperature": temperature,
        "stream": stream,
    }
    if seed is not None:
        body["seed"] = seed
    if replay:
        body["llmlb_replay"] = True
    return body


def _parse_stream(body: bytes):
    """(content_text, replay_token_ids, frame_payloads) of a chat SSE body."""
    text = []
    tokens = []
    payloads = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            continue
        obj = json.loads(data)
        payloads.append(obj)
        if obj.get("object") == "llmlb.replay":
            tokens.extend(obj["tokens"])
            continue
        for choice in obj.get("choices") or []:
            content = (choice.get("delta") or {}).get("content")
            if isinstance(content, str):
                text.append(content)
    return "".join(text), tokens, payloads


# ------------------------------------------------------------- /v1/resume


@pytest.fixture(scope="module")
def engine():
    eng = _build_engine()
    yield eng
    eng.shutdown()


@pytest.mark.parametrize("temperature,seed", [(0.0, None), (0.9, 1234)])
def test_resume_replay_token_identical(engine, temperature, seed):
    """An armed stream ships replay frames whose ids always cover the text
    already emitted; replaying any committed prefix through /v1/resume
    reproduces the FULL stream token-identically (greedy and seeded)."""
    async def run():
        client = await _client(engine)
        try:
            body = _chat_body(engine, max_tokens=16,
                              temperature=temperature, seed=seed)
            resp = await client.post("/v1/chat/completions", json=body)
            assert resp.status == 200
            full_text, tokens, _ = _parse_stream(await resp.read())
            assert tokens, "armed stream must carry llmlb.replay frames"

            for cut in (0, len(tokens) // 2, len(tokens)):
                committed = tokens[:cut]
                resume_body = dict(body)
                resume_body["committed_ids"] = committed
                r2 = await client.post("/v1/resume", json=resume_body)
                assert r2.status == 200, await r2.text()
                text2, tokens2, _ = _parse_stream(await r2.read())
                assert text2 == full_text, (
                    f"resume from {cut} committed tokens diverged"
                )
                assert tokens2 == tokens
        finally:
            await client.close()
    asyncio.run(run())


def test_resume_non_streaming_and_validation(engine):
    async def run():
        client = await _client(engine)
        try:
            body = _chat_body(engine, max_tokens=8)
            resp = await client.post("/v1/chat/completions", json=body)
            _, tokens, _ = _parse_stream(await resp.read())

            nb = _chat_body(engine, stream=False, max_tokens=8, replay=False)
            nb["committed_ids"] = tokens[:2]
            r2 = await client.post("/v1/resume", json=nb)
            assert r2.status == 200
            out = await r2.json()
            assert out["object"] == "chat.completion"
            assert out["usage"]["completion_tokens"] >= len(tokens)

            bad = _chat_body(engine, stream=False, replay=False)
            bad["committed_ids"] = ["nope"]
            r3 = await client.post("/v1/resume", json=bad)
            assert r3.status == 400
            assert "committed_ids" in (await r3.json())["error"]["message"]
        finally:
            await client.close()
    asyncio.run(run())


def test_unarmed_stream_has_no_replay_frames(engine):
    """Without llmlb_replay the wire is byte-identical to the historical
    stream: no gateway-internal frames leak to direct clients."""
    async def run():
        client = await _client(engine)
        try:
            resp = await client.post(
                "/v1/chat/completions",
                json=_chat_body(engine, max_tokens=6, replay=False),
            )
            body = await resp.read()
            assert b"llmlb.replay" not in body
        finally:
            await client.close()
    asyncio.run(run())


# ------------------------------------------------------------------- drain


def test_drain_rejects_new_admissions_with_retry_after():
    eng = _build_engine()
    try:
        async def run():
            client = await _client(eng)
            try:
                r = await client.post("/api/drain", json={"grace_s": 30})
                assert r.status == 200
                info = await r.json()
                assert info["draining"] is True

                # /api/health keeps answering and advertises draining
                h = await client.get("/api/health")
                assert h.status == 200
                hb = await h.json()
                assert hb["status"] == "draining"
                assert hb["draining"]["draining"] is True

                # new /v1 admissions 503 with Retry-After from the grace
                r2 = await client.post(
                    "/v1/chat/completions",
                    json=_chat_body(eng, stream=False, replay=False),
                )
                assert r2.status == 503
                retry_after = int(r2.headers["Retry-After"])
                assert 1 <= retry_after <= 30
                err = await r2.json()
                assert err["error"]["code"] == "draining"

                # /metrics exports the drain gauge
                m = await client.get("/metrics")
                text = await m.text()
                assert "llmlb_engine_drain_state 1" in text
            finally:
                await client.close()
        asyncio.run(run())
    finally:
        eng.shutdown()


def test_drain_lets_inflight_finish_within_grace():
    eng = _build_engine()
    try:
        async def run():
            client = await _client(eng)
            try:
                # start a short stream, then drain while it runs
                resp_task = asyncio.create_task(client.post(
                    "/v1/chat/completions",
                    json=_chat_body(eng, max_tokens=10, replay=False),
                ))
                await asyncio.sleep(0.05)
                r = await client.post("/api/drain", json={"grace_s": 20})
                assert (await r.json())["draining"] is True
                resp = await resp_task
                body = await resp.read()
                assert resp.status == 200
                assert b"data: [DONE]" in body
                # nothing was parked: the stream finished inside the grace
                assert eng.core.metrics.drain_parked_total == 0
            finally:
                await client.close()
        asyncio.run(run())
    finally:
        eng.shutdown()


def test_drain_parks_and_aborts_stragglers_after_grace():
    from llmlb_tpu.engine.scheduler import SamplingParams

    # a big slot so the straggler stream genuinely outlives the grace on a
    # fast CPU engine (debug-tiny decodes hundreds of tok/s once compiled)
    eng = _build_engine(slot_capacity=2048)
    try:
        async def run():
            client = await _client(eng)
            try:
                # probe for a seed with no early EOS (same trick as the
                # PR 10 bench): the straggler must still be decoding when
                # the grace expires
                prompt_ids = eng.encode_chat(
                    [{"role": "user", "content": "the quick brown fox"}]
                )
                seed = None
                for s in range(30):
                    probe = await eng.complete(prompt_ids, SamplingParams(
                        temperature=0.9, seed=s, max_tokens=300,
                    ))
                    if probe.finish_reason == "length":
                        seed = s
                        break
                assert seed is not None, "no 300-token seed in 30 tries"

                # a long stream that cannot finish inside the tiny grace
                resp = await client.post(
                    "/v1/chat/completions",
                    json=_chat_body(eng, max_tokens=1900, temperature=0.9,
                                    seed=seed, replay=True),
                )
                assert resp.status == 200
                # wait until DECODE is demonstrably underway (several
                # content deltas seen) — a slot still prefilling cannot
                # park; only decoding stragglers exercise the park path
                got = b""
                content_frames = 0
                while content_frames < 5:
                    line = await resp.content.readline()
                    got += line
                    if line.startswith(b"data:") and b'"content"' in line:
                        content_frames += 1

                r = await client.post("/api/drain", json={"grace_s": 0.05})
                assert (await r.json())["draining"] is True

                # the connection must be hard-cut (the gateway-side signal
                # for resume), not cleanly finished
                cut = False
                try:
                    rest = await resp.content.read()
                    if b"data: [DONE]" not in got + rest:
                        cut = True
                except Exception:
                    cut = True
                assert cut, "straggler stream was not aborted at grace expiry"

                # the slot was parked through the PR 10 park path
                deadline = asyncio.get_running_loop().time() + 5.0
                while (eng.core.metrics.drain_parked_total == 0
                       and asyncio.get_running_loop().time() < deadline):
                    await asyncio.sleep(0.05)
                assert eng.core.metrics.drain_parked_total >= 1
                assert eng.core.stats().active_slots == 0
            finally:
                await client.close()
        asyncio.run(run())
    finally:
        eng.shutdown()
