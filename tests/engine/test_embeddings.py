"""/v1/embeddings on the engine: OpenAI contract, normalization, batching."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine


@pytest.fixture(scope="module")
def engine():
    eng = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64,
        prefill_buckets=(16, 32), seed=0,
    )
    yield eng
    eng.shutdown()


async def _client(engine) -> TestClient:
    client = TestClient(TestServer(create_engine_app(engine, owns_engine=False)))
    await client.start_server()
    return client


def test_embed_service_normalized_and_deterministic(engine):
    async def run():
        ids = engine.tokenizer.encode("embedding test input")
        a = await engine.embed([ids])
        b = await engine.embed([ids])
        va = np.asarray(a[0])
        assert va.shape == (engine.core.cfg.hidden_size,)
        np.testing.assert_allclose(np.linalg.norm(va), 1.0, rtol=1e-5)
        np.testing.assert_allclose(va, np.asarray(b[0]), rtol=1e-6)
    asyncio.run(run())


def test_embeddings_route_openai_contract(engine):
    async def run():
        client = await _client(engine)
        try:
            resp = await client.post("/v1/embeddings", json={
                "model": engine.model_id,
                "input": ["first text", "second text"],
            })
            assert resp.status == 200
            body = await resp.json()
            assert body["object"] == "list"
            assert len(body["data"]) == 2
            assert body["data"][0]["object"] == "embedding"
            assert body["data"][1]["index"] == 1
            assert body["usage"]["prompt_tokens"] > 0
            # different texts -> different vectors
            v0 = np.asarray(body["data"][0]["embedding"])
            v1 = np.asarray(body["data"][1]["embedding"])
            assert not np.allclose(v0, v1)
        finally:
            await client.close()
    asyncio.run(run())


def test_embeddings_route_token_array_and_errors(engine):
    async def run():
        client = await _client(engine)
        try:
            ids = engine.tokenizer.encode("hello")
            resp = await client.post("/v1/embeddings", json={"input": ids})
            assert resp.status == 200
            body = await resp.json()
            assert len(body["data"]) == 1

            resp = await client.post("/v1/embeddings", json={})
            assert resp.status == 400
            resp = await client.post("/v1/embeddings", json={"input": []})
            assert resp.status == 400
        finally:
            await client.close()
    asyncio.run(run())


def test_models_advertises_embeddings_capability(engine):
    async def run():
        client = await _client(engine)
        try:
            resp = await client.get("/v1/models")
            body = await resp.json()
            caps = body["data"][0]["capabilities"]
            assert "chat_completion" in caps and "embeddings" in caps
        finally:
            await client.close()
    asyncio.run(run())
