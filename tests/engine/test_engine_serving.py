"""Engine serving tests: continuous batching, streaming, OpenAI contract."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.engine.scheduler import SamplingParams
from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine


# The whole serving contract runs over BOTH KV layouts: paged (default —
# shared page pool + block tables) and dense (the original slot cache) —
# plus the paged layout with the int8 quantization knob EXPLICITLY off,
# proving the quantization plumbing is zero-cost when disabled
# (docs/quantization.md; bit-identity itself is pinned by
# test_quantized_serving.test_quantize_off_bit_identical).
@pytest.fixture(scope="module",
                params=["paged", "dense", "paged-quantize-off"])
def engine(request):
    layout = "dense" if request.param == "dense" else "paged"
    extra = ({"quantize": "off"} if request.param == "paged-quantize-off"
             else {})
    eng = Engine.from_preset(
        "debug-tiny", num_slots=4, slot_capacity=64,
        prefill_buckets=(16, 32), seed=0,
        kv_layout=layout, kv_page_size=16, **extra,
    )
    yield eng
    eng.shutdown()


async def _client(engine) -> TestClient:
    client = TestClient(TestServer(create_engine_app(engine, owns_engine=False)))
    await client.start_server()
    return client


def test_direct_complete_deterministic(engine):
    async def run():
        ids = engine.tokenizer.encode("hello world")
        a = await engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        b = await engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        assert a.completion_tokens == b.completion_tokens
        assert a.text == b.text
        assert a.prompt_tokens == len(ids)
    asyncio.run(run())


def test_concurrent_requests_all_complete(engine):
    """More requests than slots: continuous batching must drain the queue."""
    async def run():
        ids = engine.tokenizer.encode("abc")
        results = await asyncio.gather(*[
            engine.complete(ids, SamplingParams(temperature=0.8, max_tokens=6))
            for _ in range(10)
        ])
        for r in results:
            assert r.finish_reason in ("stop", "length")
            assert r.completion_tokens >= 1
    asyncio.run(run())


def test_chat_completions_non_stream(engine):
    async def run():
        client = await _client(engine)
        try:
            resp = await client.post("/v1/chat/completions", json={
                "model": engine.model_id,
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5, "temperature": 0,
            })
            assert resp.status == 200
            body = await resp.json()
            assert body["object"] == "chat.completion"
            assert body["choices"][0]["finish_reason"] in ("stop", "length")
            usage = body["usage"]
            assert usage["prompt_tokens"] > 0
            assert usage["total_tokens"] == (
                usage["prompt_tokens"] + usage["completion_tokens"]
            )
        finally:
            await client.close()
    asyncio.run(run())


def test_chat_completions_stream_has_usage_final_chunk(engine):
    """The gateway's TPS tracker depends on usage in the final SSE payload."""
    async def run():
        client = await _client(engine)
        try:
            resp = await client.post("/v1/chat/completions", json={
                "model": engine.model_id,
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5, "temperature": 0, "stream": True,
                "stream_options": {"include_usage": True},
            })
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/event-stream")
            raw = (await resp.read()).decode()
            chunks = [
                json.loads(line[len("data: "):])
                for line in raw.splitlines()
                if line.startswith("data: ") and line != "data: [DONE]"
            ]
            assert raw.strip().endswith("data: [DONE]")
            # some chunk carries content; last chunk carries usage w/ empty choices
            assert any(
                c["choices"] and c["choices"][0]["delta"].get("content")
                for c in chunks if c.get("choices")
            )
            final = chunks[-1]
            assert final["usage"]["completion_tokens"] >= 1
            assert final["choices"] == []
            # a finish_reason chunk precedes the usage chunk
            assert any(
                c["choices"] and c["choices"][0]["finish_reason"]
                for c in chunks if c.get("choices")
            )
        finally:
            await client.close()
    asyncio.run(run())


def test_responses_api_stream_events(engine):
    async def run():
        client = await _client(engine)
        try:
            resp = await client.post("/v1/responses", json={
                "model": engine.model_id, "input": "hello",
                "max_output_tokens": 5, "temperature": 0, "stream": True,
            })
            assert resp.status == 200
            raw = (await resp.read()).decode()
            events = [l.split(": ", 1)[1] for l in raw.splitlines()
                      if l.startswith("event: ")]
            assert events[0] == "response.created"
            assert "response.output_text.delta" in events
            assert events[-1] == "response.completed"
            completed = [
                json.loads(l[len("data: "):]) for l in raw.splitlines()
                if l.startswith("data: ")
            ][-1]
            assert completed["response"]["status"] == "completed"
            assert completed["response"]["usage"]["output_tokens"] >= 1
        finally:
            await client.close()
    asyncio.run(run())


def test_models_health_system(engine):
    async def run():
        client = await _client(engine)
        try:
            models = await (await client.get("/v1/models")).json()
            assert models["data"][0]["id"] == engine.model_id

            health = await (await client.get("/api/health")).json()
            assert health["status"] == "ok"
            assert health["tpu"]["chip_count"] >= 1
            assert "hbm_used_bytes" in health["tpu"]
            assert health["engine"]["num_slots"] == 4

            system = await (await client.get("/api/system")).json()
            assert system["tpu_engine"] is True
        finally:
            await client.close()
    asyncio.run(run())


def test_validation_errors(engine):
    async def run():
        client = await _client(engine)
        try:
            r = await client.post("/v1/chat/completions", json={"messages": []})
            assert r.status == 400
            r = await client.post("/v1/chat/completions", data=b"not json")
            assert r.status == 400
            r = await client.post("/v1/chat/completions", json={
                "messages": [{"role": "user", "content": "x"}], "n": 3,
            })
            assert r.status == 400
            # prompt longer than the largest prefill bucket
            r = await client.post("/v1/completions", json={
                "prompt": "x" * 200, "max_tokens": 2,
            })
            assert r.status in (400, 500)
        finally:
            await client.close()
    asyncio.run(run())


def test_multichar_stop_straddling_deltas(engine):
    """A stop sequence split across token deltas must be fully truncated."""
    async def run():
        ids = engine.tokenizer.encode("q")
        first = await engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=10))
        if len(first.text) < 4:
            pytest.skip("tiny model emitted too little text")
        # pick a 3-char stop from the middle: with a byte tokenizer each char
        # arrives in its own delta, so the stop always straddles deltas
        mid = len(first.text) // 2
        stop_seq = first.text[mid : mid + 3]
        stopped = await engine.complete(
            ids, SamplingParams(temperature=0.0, max_tokens=10), stop=[stop_seq]
        )
        assert stopped.text == first.text[:mid]
        assert stop_seq not in stopped.text
        assert stopped.finish_reason == "stop"
    asyncio.run(run())


def test_early_stop_frees_slot(engine):
    """Cancellation on stop-hit must release the slot well before max_tokens."""
    async def run():
        ids = engine.tokenizer.encode("q")
        first = await engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        if not first.text:
            pytest.skip("tiny model emitted no text")
        stop_char = first.text[0]
        await engine.complete(
            ids, SamplingParams(temperature=0.0, max_tokens=4096), stop=[stop_char]
        )
        # the cancelled request's slot must drain promptly
        for _ in range(100):
            if engine.core.stats().active_slots == 0:
                break
            await asyncio.sleep(0.05)
        assert engine.core.stats().active_slots == 0
    asyncio.run(run())


def test_explicit_zero_sampling_params_rejected(engine):
    async def run():
        client = await _client(engine)
        try:
            for body in (
                {"messages": [{"role": "user", "content": "x"}], "max_tokens": 0},
                {"messages": [{"role": "user", "content": "x"}], "top_p": 0},
                {"messages": [{"role": "user", "content": "x"}], "temperature": -1},
            ):
                r = await client.post("/v1/chat/completions", json=body)
                assert r.status == 400, await r.text()
        finally:
            await client.close()
    asyncio.run(run())


def test_stop_sequence_truncates(engine):
    async def run():
        ids = engine.tokenizer.encode("q")
        # every generated byte is a candidate; use a 1-char stop drawn from output
        first = await engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        if not first.text:
            pytest.skip("random tiny model emitted no decodable text")
        stop_char = first.text[len(first.text) // 2]
        stopped = await engine.complete(
            ids, SamplingParams(temperature=0.0, max_tokens=8), stop=[stop_char]
        )
        assert stop_char not in stopped.text
        assert stopped.finish_reason == "stop"
    asyncio.run(run())


def test_engine_metrics_histograms_and_prometheus():
    """VERDICT r2 weak 8: the engine records TTFT/ITL histograms and exposes
    Prometheus text with queue/slot gauges."""
    import numpy as np

    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams

    cfg = get_preset("debug-tiny")
    core = EngineCore(cfg, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), seed=0)
    core.start()
    try:
        rng = np.random.default_rng(0)
        reqs = [
            Request(prompt_ids=list(rng.integers(1, cfg.vocab_size, size=(8,))),
                    sampling=SamplingParams(temperature=0.0, max_tokens=5))
            for _ in range(2)
        ]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            while True:
                kind, _ = r.events.get(timeout=120)
                if kind in ("done", "error"):
                    break
        m = core.metrics.summary()
        assert m["requests_total"] == 2
        assert m["tokens_total"] >= 8  # 2 requests x >=4 emitted tokens
        assert m["ttft_p50_s"] is not None
        assert m["itl_p50_s"] is not None

        stats = core.stats()
        text = core.metrics.render(
            queue_depth=stats.queued, active_slots=stats.active_slots,
            num_slots=stats.num_slots,
        )
        assert "llmlb_engine_ttft_seconds_bucket" in text
        assert 'le="+Inf"' in text
        assert "llmlb_engine_requests_total 2" in text
        # histogram invariant: +Inf cumulative equals count
        import re

        inf = int(re.search(
            r'llmlb_engine_ttft_seconds_bucket\{le="\+Inf"\} (\d+)', text
        ).group(1))
        count = int(re.search(
            r"llmlb_engine_ttft_seconds_count (\d+)", text).group(1))
        assert inf == count == 2
    finally:
        core.stop()


async def test_engine_server_prometheus_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    app = create_engine_app(engine)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = await resp.text()
        assert "llmlb_engine_num_slots 2" in text
        # health carries the compact summary for the gateway
        health = await (await client.get("/api/health")).json()
        assert "metrics" in health
        assert "ttft_p50_s" in health["metrics"]
    finally:
        await client.close()
        engine.core.stop()


async def test_engine_server_profile_endpoint(tmp_path):
    """POST /debug/profile captures a jax.profiler trace of the serving loop
    and rejects invalid durations gracefully (SURVEY §5 profiling hook)."""
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    app = create_engine_app(engine)
    client = TestClient(TestServer(app))
    await client.start_server()
    os.environ["LLMLB_TRACE_DIR"] = str(tmp_path)
    try:
        resp = await client.post("/debug/profile", json={"seconds": 0.2})
        assert resp.status == 200
        body = await resp.json()
        # traces are confined to the server-controlled root: the engine port
        # is unauthenticated, so clients must not pick write paths
        assert body["trace_dir"].startswith(str(tmp_path))
        captured = []
        for _root, _dirs, files in os.walk(body["trace_dir"]):
            captured += files
        assert captured, "profiler produced no trace files"

        # invalid durations are rejected with a structured 400
        resp = await client.post("/debug/profile", json={"seconds": "abc"})
        assert resp.status == 400
        resp = await client.post("/debug/profile", json=[1])
        assert resp.status == 400
    finally:
        os.environ.pop("LLMLB_TRACE_DIR", None)
        await client.close()
        engine.core.stop()
