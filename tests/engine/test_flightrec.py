"""Request flight recorder (llmlb_tpu/engine/flightrec.py): unit semantics,
CPU-engine lifecycle coverage, the HTTP timeline surface, the < 1%
overhead budget, and the LLMLB_FLIGHTREC=0 bit-identical guarantee.
"""

import time

import pytest

from llmlb_tpu.engine.flightrec import EVENTS, FlightRecorder, gateway_rid

# -------------------------------------------------------------- id stripping


def test_gateway_rid_strips_engine_suffix():
    assert gateway_rid("req-abc.0123abcd") == "req-abc"
    # only the 8-hex engine suffix strips; other dots stay
    assert gateway_rid("a.b.c") == "a.b.c"
    assert gateway_rid("deadbeefcafe") == "deadbeefcafe"
    # idempotent on already-stripped ids
    assert gateway_rid(gateway_rid("x.12345678")) == "x"


# ------------------------------------------------------------- recorder units


def _recorder(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("spool_dir", None)
    return FlightRecorder(**kw)


def test_emit_and_timeline_orders_by_seq():
    rec = _recorder()
    rid = "req-1"
    rec.emit(f"{rid}.aabbccdd", "admitted", prompt_tokens=5)
    rec.emit(f"{rid}.aabbccdd", "prefill_chunk", tokens=5, cached_tokens=0)
    rec.emit(f"{rid}.aabbccdd", "finished", reason="stop", generated=3)
    tl = rec.timeline(rid)
    assert tl is not None
    assert tl["request_id"] == rid
    names = [e["event"] for e in tl["events"]]
    assert names == ["admitted", "prefill_chunk", "finished"]
    seqs = [e["seq"] for e in tl["events"]]
    assert seqs == sorted(seqs)
    # engine-internal id preserved for debugging, gateway id is the key
    assert tl["events"][0]["engine_request_id"] == f"{rid}.aabbccdd"
    assert tl["events"][0]["attrs"]["prompt_tokens"] == 5
    # timestamps are wall-clock and monotone within one process
    ts = [e["ts"] for e in tl["events"]]
    assert ts == sorted(ts)
    assert abs(ts[0] - time.time()) < 60
    # unknown id: None, not an empty shell
    assert rec.timeline("nope") is None


def test_per_request_deque_bounds_and_drop_counter():
    rec = _recorder(events_per_request=8)
    for i in range(20):
        rec.emit("r", "spec_accept", drafted=2, accepted=i)
    tl = rec.timeline("r")
    assert len(tl["events"]) == 8
    assert tl["dropped"] == 12
    assert rec.events_dropped_total == 12
    # newest survive (the deque drops from the head)
    assert tl["events"][-1]["attrs"]["accepted"] == 19


def test_max_requests_evicts_least_recently_touched():
    rec = _recorder(max_requests=2)
    rec.emit("a", "admitted")
    rec.emit("b", "admitted")
    rec.emit("a", "finished", reason="stop")  # touch a: b is now oldest
    rec.emit("c", "admitted")  # evicts b
    assert rec.timeline("b") is None
    assert rec.timeline("a") is not None
    assert rec.timeline("c") is not None
    assert rec.requests_total == 3


def test_counters_queue_and_service_seconds():
    rec = _recorder()
    rec.emit("r", "admitted")
    rec.emit("r", "prefill_chunk", tokens=4)
    rec.emit("r", "finished", reason="stop")
    c = rec.counters()
    assert c["enabled"] is True
    assert c["events_total"] == 3
    assert c["by_event"] == {"admitted": 1, "prefill_chunk": 1, "finished": 1}
    assert c["queue_seconds_total"] >= 0.0
    assert c["service_seconds_total"] >= 0.0
    assert c["requests_tracked"] == 1


def test_disabled_recorder_is_inert():
    rec = _recorder(enabled=False)
    rec.emit("r", "admitted")
    assert rec.timeline("r") is None
    c = rec.counters()
    assert c["enabled"] is False
    assert c["events_total"] == 0


def test_event_taxonomy_is_closed():
    """Every event name the engine emits is in the documented taxonomy —
    the docs table and the merge logic key off these exact strings."""
    import re
    from pathlib import Path

    src_dir = Path(__file__).resolve().parents[2] / "llmlb_tpu"
    emitted: set[str] = set()
    pat = re.compile(
        r"(?:_fr_emit|flightrec\.emit)\(\s*[^,]+,\s*\"([a-z_]+)\"")
    for path in src_dir.rglob("*.py"):
        for m in pat.finditer(path.read_text()):
            emitted.add(m.group(1))
    assert emitted, "no emit sites found — pattern drifted?"
    unknown = emitted - set(EVENTS)
    assert not unknown, f"emitted events missing from EVENTS: {unknown}"


# ------------------------------------------------------------------- spooling


def test_spool_sibling_merge(tmp_path):
    """Two recorders sharing a spool dir (the chaos-drill survivor case):
    each serves the OTHER's events, deduped, in one causal timeline."""
    spool = str(tmp_path / "spool")
    a = _recorder(spool_dir=spool, source="engine-a")
    b = _recorder(spool_dir=spool, source="engine-b")
    a.emit("r.11112222", "admitted")
    a.emit("r.11112222", "prefill_chunk", tokens=4)
    a.emit("r.11112222", "handoff_emitted", tokens=2)
    b.emit("r.33334444", "adopted", committed=2)
    b.emit("r.33334444", "finished", reason="stop")

    # the survivor (b) answers for the dead engine (a)'s events too
    tl = b.timeline("r")
    srcs = [e["src"] for e in tl["events"]]
    assert "engine-a" in srcs and "engine-b" in srcs
    names = [e["event"] for e in tl["events"]]
    assert names.index("handoff_emitted") < names.index("adopted")
    # and no duplicates: b's own in-memory events dedupe against its spool
    keys = [(e["src"], e["seq"]) for e in tl["events"]]
    assert len(keys) == len(set(keys))
    assert len(tl["events"]) == 5


def test_spool_tolerates_torn_tail(tmp_path):
    spool = tmp_path / "spool"
    rec = _recorder(spool_dir=str(spool), source="engine-a")
    rec.emit("r", "admitted")
    # simulate a SIGKILL mid-write: a torn, non-JSON tail line
    path = next(spool.glob("req-*.jsonl"))
    with open(path, "a") as f:
        f.write('{"seq": 99, "ts"')
    fresh = _recorder(spool_dir=str(spool), source="engine-b")
    tl = fresh.timeline("r")
    assert [e["event"] for e in tl["events"]] == ["admitted"]


def test_spool_filename_sanitized(tmp_path):
    spool = tmp_path / "spool"
    rec = _recorder(spool_dir=str(spool), source="e")
    rec.emit("../../etc/passwd", "admitted")
    for p in spool.iterdir():
        assert p.parent == spool
        assert "/" not in p.name


# ------------------------------------------------------------------ e2e (CPU)


@pytest.fixture(scope="module")
def served_engine():
    from llmlb_tpu.engine.service import Engine

    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    yield engine
    engine.shutdown()


async def test_engine_lifecycle_events_and_timeline_endpoint(served_engine):
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.scheduler import SamplingParams
    from llmlb_tpu.engine.server import create_engine_app

    engine = served_engine
    rid = "gw-req-timeline-1"
    await engine.complete(
        [1, 2, 3, 4, 5],
        SamplingParams(temperature=0.0, max_tokens=6),
        request_id=rid,
    )
    tl = engine.core.flightrec.timeline(rid)
    assert tl is not None
    names = [e["event"] for e in tl["events"]]
    # the minimal lifecycle: admitted → at least one prefill dispatch →
    # terminal finish, in that order
    assert names[0] == "admitted"
    assert "prefill_chunk" in names
    assert names[-1] == "finished"
    assert names.index("admitted") < names.index("prefill_chunk")
    fin = tl["events"][-1]
    assert fin["attrs"]["reason"] in ("stop", "length")
    assert fin["attrs"]["generated"] >= 1

    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    try:
        resp = await client.get(f"/api/requests/{rid}/timeline")
        assert resp.status == 200
        body = await resp.json()
        assert body["request_id"] == rid
        assert [e["event"] for e in body["events"]] == names

        assert (await client.get(
            "/api/requests/never-seen/timeline")).status == 404

        # aggregate counters ride /api/steps…
        steps = await (await client.get("/api/steps")).json()
        fr = steps["flightrec"]
        assert fr["enabled"] is True
        assert fr["events_total"] >= len(names)
        assert fr["by_event"].get("admitted", 0) >= 1

        # …and /metrics exposes the documented series
        text = await (await client.get("/metrics")).text()
        assert "llmlb_engine_flightrec_events_total" in text
        assert "llmlb_engine_flightrec_queue_seconds_total" in text
        assert "llmlb_engine_flightrec_service_seconds_total" in text
    finally:
        await client.close()


async def test_slow_step_names_victims(served_engine):
    """Satellite: a flagged dispatch's StepRecord carries slot→request-id,
    and the victims' flight records gain a slow_step event."""
    from llmlb_tpu.engine.scheduler import SamplingParams

    engine = served_engine
    rid = "gw-req-victim-1"
    stats = engine.core.step_stats
    # arm the detector: it stays silent for its first 16 steps per kind
    await engine.complete(
        [2, 4, 6], SamplingParams(temperature=0.0, max_tokens=24)
    )
    # force every post-warmup step to flag: zero floor, impossible ratio
    old_ratio, old_floor = stats.slow_ratio, stats.slow_floor_s
    stats.slow_ratio = 0.0
    stats.slow_floor_s = 0.0
    try:
        await engine.complete(
            [9, 8, 7], SamplingParams(temperature=0.0, max_tokens=4),
            request_id=rid,
        )
    finally:
        stats.slow_ratio, stats.slow_floor_s = old_ratio, old_floor
    snap = stats.snapshot(slow_only=True)
    named = [r for r in snap["records"] if rid in r["request_ids"].values()]
    assert named, "no slow StepRecord names the victim request"
    tl = engine.core.flightrec.timeline(rid)
    slow = [e for e in tl["events"] if e["event"] == "slow_step"]
    assert slow, "victim's flight record lacks the slow_step event"
    assert slow[0]["attrs"]["kind"] in ("prefill", "decode", "verify")
    assert slow[0]["attrs"]["step_seq"] >= 1


async def test_flightrec_disabled_is_bit_identical(served_engine):
    """LLMLB_FLIGHTREC=0 acceptance: identical token output, zero events."""
    from llmlb_tpu.engine.scheduler import SamplingParams

    engine = served_engine
    prompt = [3, 1, 4, 1, 5]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    on = await engine.complete(prompt, sp, request_id="bit-on")

    real = engine.core.flightrec
    engine.core.flightrec = FlightRecorder(enabled=False, spool_dir=None)
    try:
        off = await engine.complete(prompt, sp, request_id="bit-off")
        assert engine.core.flightrec.events_total == 0
        assert engine.core.flightrec.timeline("bit-off") is None
    finally:
        engine.core.flightrec = real
    assert off.token_ids == on.token_ids
    assert off.text == on.text


async def test_flightrec_overhead_under_one_percent(served_engine):
    """Acceptance: one emit() (the cost each lifecycle edge adds) must be
    < 1% of the measured mean CPU-engine step — and a request crosses only
    a handful of edges over MANY steps, so the real overhead is far lower
    still. Mirrors the PR 6 StepRecord budget test."""
    from llmlb_tpu.engine.scheduler import SamplingParams

    engine = served_engine
    await engine.complete(
        [1, 2, 3], SamplingParams(temperature=0.0, max_tokens=16)
    )
    hist = engine.core.metrics.decode_step
    assert hist.n > 0
    mean_step_s = hist.total / hist.n

    rec = _recorder()
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        rec.emit(f"r{i % 64}.aabbccdd", "prefill_chunk",
                 tokens=16, cached_tokens=0)
    per_emit = (time.perf_counter() - t0) / n
    assert per_emit < 0.01 * mean_step_s, (
        f"flight-recorder emit {per_emit * 1e6:.1f}µs vs mean step "
        f"{mean_step_s * 1e3:.3f}ms — over the 1% budget"
    )
    # the disabled path is cheaper still: no clock read, no lock
    off = _recorder(enabled=False)
    t0 = time.perf_counter()
    for i in range(n):
        off.emit("r.aabbccdd", "prefill_chunk", tokens=16)
    per_noop = (time.perf_counter() - t0) / n
    assert per_noop <= per_emit
