"""Fused decode dispatch (docs/fused-decode.md).

The acceptance bars for the one-program decode step:

- PARITY GRID: over {bf16, int8-KV} x {LoRA on/off} x {spec verify off/on}
  a mixed batch of constrained + free requests, greedy AND seeded, produces
  token-identical streams from a fused engine and a legacy (fused off)
  engine. Interpret-mode CPU JAX, real scheduler.
- ONE DISPATCH: every decode/verify step record on the fused engine counts
  exactly one device program, and constrained slots never force the batch
  into single-step decode (constrained_burst_fallback_total == 0).
- PIN: LLMLB_FUSED_DECODE=0 resolves to the legacy path (and the grid
  proves legacy output unchanged by this PR); default is on for paged
  layout, off for dense.
"""

import numpy as np
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.tokenizer import ByteTokenizer
from llmlb_tpu.lora import save_adapter
from llmlb_tpu.structured import ConstraintCompiler

CFG = get_preset("debug-tiny")
TOK = ByteTokenizer(CFG.vocab_size)

# repetitive prompt: prompt-lookup speculation finds n-gram matches, so the
# spec legs of the grid actually exercise the verify path
PROMPT = [5, 6, 7, 8, 9] * 5

SCHEMA = {
    "type": "object",
    "properties": {
        "ok": {"type": "boolean"},
        "tag": {"enum": ["alpha", "beta"]},
    },
    "required": ["ok", "tag"],
}


@pytest.fixture(scope="module")
def lora_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("fused_adapters")
    save_adapter(str(d), "acme", CFG, rank=4)
    return str(d)


def _drain(request: Request) -> tuple[list[int], str]:
    toks = []
    while True:
        kind, val = request.events.get(timeout=120)
        if kind == "token":
            toks.append(val)
        elif kind == "done":
            return toks, str(val)
        else:
            raise RuntimeError(val)


def _core(*, fused: bool, quant: str | None, lora_dir: str | None,
          spec: bool) -> EngineCore:
    core = EngineCore(
        CFG, num_slots=4, slot_capacity=128, prefill_buckets=(16, 32),
        kv_layout="paged", kv_page_size=16, seed=0, quantize=quant,
        lora_dir=lora_dir, spec_decode=spec, fused_decode=fused,
        eos_id=TOK.eos_id,
    )
    # the service layer normally installs this; the grid drives the raw core
    core.constraint_compiler = ConstraintCompiler(TOK, CFG.vocab_size)
    core.start()
    return core


def _mixed_batch(core: EngineCore, lora: str | None) -> list[list[int]]:
    """Submit the 4-request mixed batch (constrained greedy, constrained
    seeded, free greedy, free seeded) and return the 4 token streams."""
    reqs = [
        Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
            temperature=0.0, max_tokens=24, lora=lora,
            constraint={"type": "json_schema", "schema": SCHEMA})),
        Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
            temperature=0.9, seed=42, max_tokens=24, lora=lora,
            constraint={"type": "json_schema", "schema": SCHEMA})),
        Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
            temperature=0.0, max_tokens=16, lora=lora)),
        Request(prompt_ids=list(PROMPT), sampling=SamplingParams(
            temperature=0.8, seed=7, max_tokens=16, lora=lora)),
    ]
    for r in reqs:
        core.submit(r)
    return [_drain(r)[0] for r in reqs]


GRID = [
    (quant, use_lora, spec)
    for quant in (None, "kv")
    for use_lora in (False, True)
    for spec in (False, True)
]


@pytest.mark.parametrize(
    "quant,use_lora,spec", GRID,
    ids=[f"{'int8kv' if q else 'bf16'}-"
         f"{'lora' if l else 'nolora'}-"
         f"{'spec' if s else 'nospec'}" for q, l, s in GRID])
def test_fused_parity_grid(lora_dir, quant, use_lora, spec):
    """Fused vs legacy token identity over the full feature grid, greedy
    and seeded, constrained and free, in one mixed batch."""
    streams = {}
    for fused in (True, False):
        core = _core(fused=fused, quant=quant,
                     lora_dir=lora_dir if use_lora else None, spec=spec)
        try:
            streams[fused] = _mixed_batch(
                core, "acme" if use_lora else None)
            if fused:
                _assert_fused_invariants(core, spec=spec)
        finally:
            core.stop()
    assert streams[True] == streams[False], (
        f"fused/legacy divergence (quant={quant}, lora={use_lora}, "
        f"spec={spec})")


def _assert_fused_invariants(core: EngineCore, *, spec: bool) -> None:
    # exactly ONE device program per decode/verify step
    records = core.step_stats.snapshot(limit=512)["records"]
    decs = [r for r in records if r["kind"] in ("decode", "verify")]
    assert decs, "no decode steps recorded"
    assert {r["dispatches"] for r in decs} == {1}, decs
    # constrained slots rode the burst: zero single-step fallbacks
    assert core.metrics.constrained_burst_fallback_total == 0
    assert core.metrics.fused_decode_steps_total > 0
    # the grammar actually ran on device
    assert core.metrics.masked_decode_steps_total > 0
    assert core._grammar_tables is not None
    assert core._grammar_tables.schemas_registered >= 1
    assert core._grammar_tables.schemas_rejected == 0
    if spec:
        assert core.metrics.spec_verify_steps_total > 0


# ----------------------------------------------------------- mode resolution


def test_env_pin_and_defaults(monkeypatch):
    """LLMLB_FUSED_DECODE resolves: 0 pins legacy, 1 pins fused, unset
    defaults on for paged and off for dense (the conservative default for
    the layout the fused path wasn't built around)."""
    monkeypatch.setenv("LLMLB_FUSED_DECODE", "0")
    core = EngineCore(CFG, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), kv_layout="paged", seed=0)
    assert core.fused_decode is False
    assert core._grammar_tables is None

    monkeypatch.setenv("LLMLB_FUSED_DECODE", "1")
    core = EngineCore(CFG, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), kv_layout="paged", seed=0)
    assert core.fused_decode is True
    assert core._grammar_tables is not None

    monkeypatch.delenv("LLMLB_FUSED_DECODE")
    assert EngineCore(CFG, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), kv_layout="paged",
                      seed=0).fused_decode is True
    assert EngineCore(CFG, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), kv_layout="dense",
                      seed=0).fused_decode is False

    # constructor kwarg beats the env var
    monkeypatch.setenv("LLMLB_FUSED_DECODE", "1")
    assert EngineCore(CFG, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), kv_layout="paged", seed=0,
                      fused_decode=False).fused_decode is False


# ----------------------------------------------- transition-table semantics


def test_transition_table_matches_allowed_mask():
    """table[s, v] >= 0 exactly where allowed[s, v] (modulo the dead-end
    EOS escape both sides share), and walking the table replays the host
    DFA token for token."""
    tc = ConstraintCompiler(TOK, CFG.vocab_size).compile_spec(
        {"type": "json_schema", "schema": SCHEMA})
    table = tc.transition_table()
    assert table.shape == tc.allowed.shape
    assert table.dtype == np.int32
    dead = ~tc.allowed.any(axis=1)
    assert ((table[~dead] >= 0) == tc.allowed[~dead]).all()
    for s in np.flatnonzero(dead):
        # dead ends fail open to EOS only — the bias_row deviation, mirrored
        ok = table[s] >= 0
        assert ok[tc.eos_id] and ok.sum() == 1
    # replay: host-side FSM walk == table walk for a valid document
    doc = '{"ok":true,"tag":"alpha"}'
    ids = [ord(c) for c in doc]
    s = 0
    for t in ids:
        assert tc.allowed[s, t], (s, t)
        nxt = int(table[s, t])
        assert nxt >= 0
        s = nxt
    # accepting state: EOS self-loops
    assert int(table[s, tc.eos_id]) == s


def test_grammar_tables_free_row_and_budget():
    from llmlb_tpu.ops.grammar import GrammarTables, grammar_advance, \
        grammar_bias

    tc = ConstraintCompiler(TOK, CFG.vocab_size).compile_spec(
        {"type": "json_schema", "schema": SCHEMA})

    gt = GrammarTables(CFG.vocab_size)
    off = gt.register(tc)
    assert off == 1  # row 0 is the free row
    assert gt.register(tc) == off  # idempotent per instance
    assert gt.rows == 1 + tc.allowed.shape[0]

    # free row: zero bias everywhere, cursor self-loops to 0
    bias = np.asarray(grammar_bias(gt.device(), np.array([0])))
    assert (bias == 0.0).all()
    assert int(np.asarray(
        grammar_advance(gt.device(), np.array([0]), np.array([5])))[0]) == 0

    # a one-row budget rejects registration instead of truncating
    tiny = GrammarTables(CFG.vocab_size,
                         budget_bytes=CFG.vocab_size * 4)
    assert tiny.register(tc) is None
    assert tiny.schemas_rejected == 1
