"""KV page shipping + tiered host-RAM offload (docs/kv-cache.md).

Three layers, mirroring the implementation split:
- kv_transfer unit tests: compat gating order, and every structural lie a
  payload can tell (bad magic, truncation, trailing bytes, geometry
  mismatch) raises KVTransferError — the callers count a labeled fallback
  and replay; a bad payload is never a client-visible error. The header
  round-trip auto-probe lives with the handoff wire tests
  (tests/disagg/test_handoff_wire.py).
- KVOffloadTier unit tests (pure host): budget/LRU math, the
  longer-entry-matches-on-its-head rule, parked pop/drop.
- EngineCore integration (CPU backend): a preempted request restores its
  parked pages from the host tier and continues token-identically —
  greedy AND seeded, bf16 AND int8 pools — with ZERO prefill dispatches
  for the resume (the dispatch ledger proves it); a prefix entry evicted
  under page pressure re-hits from the tier; and with both knobs off the
  engine is bit-identical to the replay-only behavior it ships today.
"""

import numpy as np
import pytest

from llmlb_tpu.engine.kv_offload import KVOffloadTier
from llmlb_tpu.engine.kv_transfer import (
    KVPages,
    KVTransferError,
    KVWireHeader,
    expected_sections,
    kv_compat_reason,
    parse_kv_payload,
    serialize_kv_pages,
)
from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams

# ---------------------------------------------------------------- wire format


def _header(**over) -> KVWireHeader:
    base = dict(version=1, layers=2, page_size=4, num_kv_heads=2,
                head_dim=4, kv_dtype="float32", tokens=6, num_pages=2)
    base.update(over)
    return KVWireHeader(**base)


def _sections(header: KVWireHeader) -> dict:
    out = {}
    for i, (name, (shape, dtype)) in enumerate(
            sorted(expected_sections(header).items())):
        n = int(np.prod(shape))
        out[name] = (np.arange(n, dtype=np.float64) % 97 + i) \
            .astype(dtype).reshape(shape)
    return out


def _payload(**over) -> dict:
    header = _header(**over)
    return serialize_kv_pages(header, _sections(header))


def test_int8_sections_roundtrip_bit_exact():
    """Quantized pools ship codes AND their f32 scales; both must land
    byte-identical (re-quantizing would be a silent numerics change)."""
    header = _header(kv_dtype="int8")
    sections = _sections(header)
    assert set(sections) == {"k_q", "k_s", "v_q", "v_s"}
    parsed = parse_kv_payload(serialize_kv_pages(header, sections))
    for name, arr in sections.items():
        assert parsed.sections[name].dtype == arr.dtype
        assert np.array_equal(parsed.sections[name], arr)


def test_serializer_refuses_shape_lies():
    """A malformed export must fail the exporter, never ship bytes an
    adopter would misread."""
    header = _header()
    sections = _sections(header)
    sections["k"] = sections["k"][:, :1]  # wrong num_pages axis
    with pytest.raises(KVTransferError, match="header"):
        serialize_kv_pages(header, sections)
    with pytest.raises(KVTransferError, match="sections"):
        serialize_kv_pages(_header(kv_dtype="int8"), _sections(_header()))


@pytest.mark.parametrize("mutate, match", [
    (lambda p: p.pop("data"), "data"),
    (lambda p: p.update(data="!!not-base64!!"), "base64"),
    (lambda p: p.update(data=p["data"][:16]), "base64|magic|truncated"),
    (lambda p: p.update(data=p["data"][:-8] + p["data"][:8]), "."),
    (lambda p: p.update(tokens=0), "tokens"),
    (lambda p: p.update(tokens=10_000), "tokens"),
    (lambda p: p.update(layers=True), "layers"),
    (lambda p: p.update(kv_dtype="float8"), "kv_dtype"),
    (lambda p: p.update(num_pages=0), "num_pages"),
])
def test_rejects_corrupted_payloads(mutate, match):
    payload = _payload()
    mutate(payload)
    with pytest.raises(KVTransferError, match=match):
        parse_kv_payload(payload)


def test_rejects_trailing_bytes():
    import base64
    payload = _payload()
    blob = base64.b64decode(payload["data"]) + b"\x00"
    payload["data"] = base64.b64encode(blob).decode("ascii")
    with pytest.raises(KVTransferError, match="trailing"):
        parse_kv_payload(payload)


def test_compat_reason_ordering():
    """dtype outranks page_size outranks geometry — the fallback counter's
    reason label names the FIRST incompatibility an operator must fix."""
    me = dict(layers=2, page_size=4, num_kv_heads=2, head_dim=4,
              kv_dtype="float32")
    assert kv_compat_reason(_header(), **me) is None
    assert kv_compat_reason(_header(kv_dtype="int8", page_size=8,
                                    layers=9), **me) == "dtype"
    assert kv_compat_reason(_header(page_size=8, layers=9),
                            **me) == "page_size"
    assert kv_compat_reason(_header(layers=9), **me) == "geometry"
    assert kv_compat_reason(_header(num_kv_heads=1), **me) == "geometry"
    assert kv_compat_reason(_header(head_dim=8), **me) == "geometry"


# ------------------------------------------------------------- offload tier


def _kvp(tokens=4, num_pages=1) -> KVPages:
    header = _header(tokens=tokens, num_pages=num_pages,
                     page_size=4, layers=1, num_kv_heads=1, head_dim=2)
    return KVPages(header=header, sections=_sections(header),
                   source="offload")


def test_tier_budget_lru_eviction():
    one = _kvp().nbytes
    tier = KVOffloadTier(budget_bytes=2 * one)
    assert tier.put_prefix(None, (1, 2, 3, 4), _kvp())
    assert tier.put_prefix(None, (5, 6, 7, 8), _kvp())
    assert tier.bytes_used == 2 * one
    # third entry evicts the LRU-oldest, never overruns the budget
    assert tier.put_parked("rid-1", _kvp())
    assert tier.bytes_used == 2 * one
    assert tier.evictions == 1
    assert tier.match_prefix(None, (1, 2, 3, 4), 4) is None  # evicted
    assert tier.match_prefix(None, (5, 6, 7, 8), 4) is not None


def test_tier_refuses_oversized_payload():
    tier = KVOffloadTier(budget_bytes=8)
    assert not tier.would_admit(_kvp().nbytes)
    assert not tier.put_prefix(None, (1,), _kvp())
    assert tier.bytes_used == 0
    assert KVOffloadTier(budget_bytes=0).would_admit(1) is False


def test_tier_longer_entry_matches_on_usable_head():
    """The returning-user case: the stored entry covers the FULL prompt,
    the query can only use n-1 tokens — the entry must still match on its
    head (pages are position-independent; the caller slices)."""
    tier = KVOffloadTier(budget_bytes=1 << 20)
    stored = tuple(range(48))
    tier.put_prefix(None, stored, _kvp(tokens=48, num_pages=12))
    got = tier.match_prefix(None, list(range(48)), max_len=47)
    assert got is not None
    tokens, kvp = got
    assert tokens == stored
    assert kvp.header.tokens == 48
    # consumed on hit: the caller re-lands it into HBM
    assert tier.match_prefix(None, list(range(48)), 47) is None
    assert tier.hits == 1 and tier.misses == 1


def test_tier_mismatched_head_is_a_miss():
    tier = KVOffloadTier(budget_bytes=1 << 20)
    tier.put_prefix(None, (1, 2, 3, 4), _kvp())
    assert tier.match_prefix(None, (1, 2, 9, 4), 4) is None
    assert tier.match_prefix("other-ns", (1, 2, 3, 4), 4) is None
    assert tier.misses == 2 and tier.hits == 0


def test_tier_parked_pop_and_drop():
    tier = KVOffloadTier(budget_bytes=1 << 20)
    tier.put_parked("rid-1", _kvp())
    tier.put_parked("rid-2", _kvp())
    assert tier.pop_parked("rid-1") is not None
    assert tier.pop_parked("rid-1") is None  # one-shot
    tier.drop_parked("rid-2")  # cancelled request: bytes leave the budget
    assert tier.bytes_used == 0
    assert tier.info()["parked_entries"] == 0


# ------------------------------------------------------------- engine core


def _req(prompt, max_tokens=4, temperature=0.0, seed=None, priority=1):
    return Request(prompt_ids=list(prompt),
                   sampling=SamplingParams(temperature=temperature,
                                           max_tokens=max_tokens, seed=seed,
                                           priority=priority))


def _collect(request, timeout=120):
    toks = []
    while True:
        kind, value = request.events.get(timeout=timeout)
        if kind == "token":
            toks.append(value)
        elif kind == "error":
            raise AssertionError(f"engine error: {value}")
        else:
            return toks, value


def _park_roundtrip(*, offload, temperature=0.0, seed=None, quantize=None,
                    kv_ship=None):
    """Reference run, then the same request parked mid-decode by a
    priority-0 interloper (num_slots=1 forces the preemption) and resumed.
    Returns (ref_tokens, victim_tokens, prefill_dispatches_for_victim+
    interloper, kv_transfer_info)."""
    kw = dict(num_slots=1, slot_capacity=64, prefill_buckets=(16,),
              seed=0, kv_layout="paged", kv_page_size=16,
              prefix_cache=False, quantize=quantize)
    if kv_ship is not None:
        kw["kv_ship"] = kv_ship
    if offload:
        kw["kv_offload_bytes"] = 1 << 28
    core = EngineCore(get_preset("debug-tiny"), **kw)
    core.start()
    try:
        prompt = [3, 5, 7, 11, 13, 17, 19, 23]
        ref, _ = _collect(core.submit(_req(prompt, max_tokens=24,
                                           temperature=temperature,
                                           seed=seed, priority=2)))
        disp0 = sum(core.prefill_dispatch_by_loop.values())
        victim = core.submit(_req(prompt, max_tokens=24,
                                  temperature=temperature, seed=seed,
                                  priority=2))
        toks = []
        while len(toks) < 3:  # decoding: parked mid-generation, not queued
            kind, value = victim.events.get(timeout=60)
            assert kind == "token", (kind, value)
            toks.append(value)
        _collect(core.submit(_req([2] * 8, max_tokens=4, priority=0)))
        rest, _ = _collect(victim)
        toks += rest
        assert core.metrics.preemptions_total >= 1, "interloper never parked"
        disp = sum(core.prefill_dispatch_by_loop.values()) - disp0
        return ref, toks, disp, core.kv_transfer_info()
    finally:
        core.stop()


@pytest.mark.parametrize("quantize", [None, "kv"],
                         ids=["bf16-pool", "int8-pool"])
def test_park_restore_is_zero_prefill_and_token_identical(quantize):
    """THE acceptance invariant: a tier restore re-enters decode without a
    single prefill dispatch — 2 on the ledger (victim's own prefill + the
    interloper's) where the replay path needs >= 3 — and the tokens match
    the uninterrupted reference bit for bit, for plain AND int8 pools."""
    ref_r, toks_r, disp_replay, _ = _park_roundtrip(offload=False,
                                                    quantize=quantize)
    assert toks_r == ref_r
    assert disp_replay >= 3, "replay resume must re-prefill"
    ref, toks, disp, info = _park_roundtrip(offload=True, quantize=quantize)
    assert toks == ref == ref_r
    assert disp == 2, f"restore ran {disp - 2} prefill dispatches"
    assert info["offload"]["spills"] >= 1
    assert info["offload"]["hits"] >= 1
    assert info["restored_total"] >= 1
    assert info["restored_bytes_total"] > 0


def test_park_restore_seeded_stochastic_identity():
    ref, toks, _, info = _park_roundtrip(offload=True, temperature=0.9,
                                         seed=1234)
    assert toks == ref
    assert info["restored_total"] >= 1


def test_knobs_off_is_bit_identical_to_replay_only():
    """LLMLB_KV_SHIP=0 + LLMLB_KV_OFFLOAD_BYTES=0 pins today's behavior:
    same tokens, same dispatch count, nothing spilled, nothing counted."""
    ref_d, toks_d, disp_d, _ = _park_roundtrip(offload=False)
    ref, toks, disp, info = _park_roundtrip(offload=False, kv_ship=False)
    assert (ref, toks, disp) == (ref_d, toks_d, disp_d)
    assert info["ship_enabled"] is False
    assert info["ship_total"] == 0
    assert info["offload"]["enabled"] is False


def test_prefix_evicted_to_tier_rehits_without_reprefill():
    """Page pressure evicts prompt A's cached prefix D2H; A's return
    restores it H2D into the live radix cache and takes the ordinary
    zero-copy hit — one suffix chunk, not a full re-prefill."""
    rng = np.random.default_rng(11)
    cfg = get_preset("debug-tiny")
    A = list(rng.integers(1, cfg.vocab_size, size=(48,)))
    B = list(rng.integers(1, cfg.vocab_size, size=(48,)))
    core = EngineCore(cfg, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), seed=0, kv_layout="paged",
                      kv_page_size=16, kv_pages=6,
                      kv_offload_bytes=1 << 28)
    core.start()
    try:
        ra, _ = _collect(core.submit(_req(A)))  # caches A's prefix
        _collect(core.submit(_req(B)))  # page pressure evicts A -> tier
        assert core.kv_transfer_info()["offload"]["spills"] >= 1
        hits0 = core.metrics.prefix_hits_total
        disp0 = sum(core.prefill_dispatch_by_loop.values())
        ra2, _ = _collect(core.submit(_req(A)))
        info = core.kv_transfer_info()
        assert ra2 == ra
        assert info["offload"]["hits"] >= 1
        assert core.metrics.prefix_hits_total == hits0 + 1
        assert info["restored_total"] >= 1
        # restored head + one suffix chunk: a single prefill dispatch
        assert sum(core.prefill_dispatch_by_loop.values()) - disp0 == 1
    finally:
        core.stop()
