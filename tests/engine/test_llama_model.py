"""Model correctness: prefill/decode consistency + parity with HF transformers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.models.llama import (
    LlamaConfig,
    decode_step,
    init_kv_cache,
    init_params,
    prefill,
)

TINY = LlamaConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    rope_theta=10000.0,
    rms_eps=1e-5,
    dtype=jnp.float32,
)


def test_prefill_then_decode_matches_full_prefill():
    """Decoding token-by-token must reproduce full-prompt prefill logits."""
    cfg = TINY
    key = jax.random.PRNGKey(0)
    params = init_params(cfg, key)
    b, t_full, capacity = 2, 8, 16

    ids = jax.random.randint(jax.random.PRNGKey(1), (b, t_full), 0, cfg.vocab_size)
    lens_full = jnp.array([t_full, t_full], jnp.int32)

    ck, cv = init_kv_cache(cfg, b, capacity)
    full_logits, _, _ = prefill(params, cfg, ids, lens_full, ck, cv)

    # prefill only the first 5 tokens, then decode the remaining 3
    t0 = 5
    ck, cv = init_kv_cache(cfg, b, capacity)
    padded = jnp.zeros((b, t0), jnp.int32).at[:, :t0].set(ids[:, :t0])
    logits, ck, cv = prefill(
        params, cfg, padded, jnp.array([t0, t0], jnp.int32), ck, cv
    )
    for step in range(t0, t_full):
        logits, ck, cv = decode_step(
            params, cfg, ids[:, step], jnp.full((b,), step, jnp.int32), ck, cv
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full_logits), rtol=2e-4, atol=2e-4
    )


def test_ragged_prompt_lens_ignore_padding():
    """Padding tokens after prompt_len must not change the last-token logits."""
    cfg = TINY
    params = init_params(cfg, jax.random.PRNGKey(0))
    b, t, capacity = 2, 8, 16
    ids = jax.random.randint(jax.random.PRNGKey(2), (b, t), 0, cfg.vocab_size)
    lens = jnp.array([5, 8], jnp.int32)

    ck, cv = init_kv_cache(cfg, b, capacity)
    logits_a, _, _ = prefill(params, cfg, ids, lens, ck, cv)

    garbage = ids.at[0, 5:].set(7)  # mutate only padding of sequence 0
    ck, cv = init_kv_cache(cfg, b, capacity)
    logits_b, _, _ = prefill(params, cfg, garbage, lens, ck, cv)
    np.testing.assert_allclose(
        np.asarray(logits_a), np.asarray(logits_b), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("attention_bias,tie", [(False, False), (True, True)])
def test_matches_hf_transformers(attention_bias, tie):
    """Logit parity with HF torch Llama/Qwen2 on a random tiny checkpoint."""
    torch = pytest.importorskip("torch")
    import transformers

    torch.manual_seed(0)

    from llmlb_tpu.engine.weights import convert_hf_tensors

    if attention_bias:
        hf_cfg = transformers.Qwen2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
            tie_word_embeddings=tie,
        )
        hf_model = transformers.Qwen2ForCausalLM(hf_cfg)
    else:
        hf_cfg = transformers.LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
            tie_word_embeddings=tie, attention_bias=False,
        )
        hf_model = transformers.LlamaForCausalLM(hf_cfg)
    hf_model.eval()

    cfg = LlamaConfig.from_hf_config(hf_cfg.to_dict(), dtype=jnp.float32)
    assert cfg.attention_bias == attention_bias

    state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    params = convert_hf_tensors(cfg, lambda name: state[name])
    params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)

    b, t = 2, 7
    ids_np = np.random.default_rng(0).integers(0, 256, (b, t))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids_np)).logits[:, -1, :].numpy()

    ck, cv = init_kv_cache(cfg, b, 16)
    logits, _, _ = prefill(
        params, cfg, jnp.asarray(ids_np, jnp.int32),
        jnp.full((b,), t, jnp.int32), ck, cv,
    )
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-3, atol=2e-3)
