"""Long-context serving: chunked prefill + prefill/decode interleaving.

VERDICT r1 items 4 and 7: prompts beyond the largest one-shot prefill bucket
must stream through the engine (chunked prefill via prefill_extend_slots), and
decode slots must keep emitting tokens while a long prompt prefills.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_cfg():
    return get_preset("debug-tiny")


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return llama.init_params(tiny_cfg, jax.random.PRNGKey(0))


def test_prefill_extend_matches_oneshot(tiny_cfg, tiny_params):
    """Chunked prefill must produce the same cache + final logits as a
    one-shot prefill of the whole prompt."""
    cfg, params = tiny_cfg, tiny_params
    capacity, n = 64, 40
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, size=(n,)).astype(np.int32)

    # one-shot reference: bucket 64
    ck, cv = llama.init_kv_cache(cfg, 2, capacity)
    ids = np.zeros((1, 64), np.int32)
    ids[0, :n] = prompt
    ref_logits, ck_ref, cv_ref = llama.prefill_into_slots(
        params, cfg, jnp.asarray(ids), jnp.asarray([n], np.int32),
        jnp.asarray([1], np.int32), ck, cv,
    )

    # chunked: 16-token chunks into slot 1
    ck2, cv2 = llama.init_kv_cache(cfg, 2, capacity)
    logits = None
    for start in range(0, n, 16):
        chunk = prompt[start:start + 16]
        ids_c = np.zeros((1, 16), np.int32)
        ids_c[: , :len(chunk)] = chunk
        logits, ck2, cv2 = llama.prefill_extend_slots(
            params, cfg, jnp.asarray(ids_c),
            jnp.asarray([len(chunk)], np.int32),
            jnp.asarray([start], np.int32),
            jnp.asarray([1], np.int32), ck2, cv2,
        )

    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    # caches agree over the valid region of slot 1
    np.testing.assert_allclose(
        np.asarray(ck_ref[:, 1, :n]), np.asarray(ck2[:, 1, :n]),
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(cv_ref[:, 1, :n]), np.asarray(cv2[:, 1, :n]),
        rtol=2e-4, atol=2e-4,
    )


def test_chunked_greedy_matches_oneshot_decode(tiny_cfg, tiny_params):
    """Greedy continuation after chunked prefill == after one-shot prefill."""
    cfg, params = tiny_cfg, tiny_params
    core_a = EngineCore(cfg, tiny_params, num_slots=2, slot_capacity=96,
                        prefill_buckets=(16, 64))
    core_b = EngineCore(cfg, tiny_params, num_slots=2, slot_capacity=96,
                        prefill_buckets=(16,))  # forces chunking for n=40
    rng = np.random.default_rng(1)
    prompt = list(rng.integers(0, cfg.vocab_size, size=(40,)))
    outs = []
    for core in (core_a, core_b):
        req = Request(prompt_ids=prompt,
                      sampling=SamplingParams(temperature=0.0, max_tokens=8))
        core.submit(req)
        core.start()
        toks = []
        while True:
            kind, val = req.events.get(timeout=60)
            if kind == "token":
                toks.append(val)
            else:
                assert kind == "done", (kind, val)
                break
        core.stop()
        outs.append(toks)
    assert outs[0] == outs[1], outs


def test_decode_progresses_during_long_prefill(tiny_cfg, tiny_params):
    """Drive the step loop by hand: while a long prompt's chunks are being
    fed, the already-active slot must emit one token per iteration."""
    cfg = tiny_cfg
    core = EngineCore(cfg, tiny_params, num_slots=2, slot_capacity=256,
                      prefill_buckets=(16, 32))
    short = Request(prompt_ids=[1, 2, 3],
                    sampling=SamplingParams(temperature=0.0, max_tokens=200))
    core.submit(short)
    assert core._try_insert()
    # activated: first token sampled on device, emitted with the next
    # decode fetch (deferred — activation itself costs no host sync)
    assert core.slots[0].first_pending
    assert core._decode_active()
    assert short.first_token_at is not None

    # 130-token prompt: > largest bucket (32) -> chunked (5 chunks)
    long = Request(prompt_ids=list(range(1, 131)),
                   sampling=SamplingParams(temperature=0.0, max_tokens=4))
    core.submit(long)
    assert core._try_insert()  # claims slot, no prefill work yet
    assert core.slots[1].prefilling

    short_tokens_during_prefill = 0
    iterations = 0
    while core.slots[1].prefilling:
        did_prefill = core._advance_prefill()
        assert did_prefill
        if core.slots[1].prefilling:  # not the final chunk yet
            assert long.first_token_at is None
        before = short.events.qsize()
        assert core._decode_active()
        assert short.events.qsize() == before + 1  # decode emitted for short
        short_tokens_during_prefill += 1
        iterations += 1
        assert iterations < 50
    assert iterations == (130 + 31) // 32  # ceil(130/32) = 5 chunks
    assert short_tokens_during_prefill >= 4
    # activated on the final chunk; its first token rode the same loop
    # iteration's decode fetch (deferred emission)
    assert long.first_token_at is not None

    # run the loop to completion for the long request
    core.start()
    toks = []
    while True:
        kind, val = long.events.get(timeout=60)
        if kind == "token":
            toks.append(val)
        else:
            assert kind == "done", (kind, val)
            break
    core.stop()


def test_engine_long_prompt_streams_e2e(tiny_cfg):
    """A prompt 4x beyond the largest bucket streams a completion through the
    Engine service layer (VERDICT item 4's done-criterion at test scale)."""
    eng = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=256,
        prefill_buckets=(16, 32), seed=0,
    )
    try:
        async def run():
            ids = list(np.random.default_rng(2).integers(
                1, eng.core.cfg.vocab_size, size=(130,)))
            result = await eng.complete(
                ids, SamplingParams(temperature=0.0, max_tokens=6))
            assert result.prompt_tokens == 130
            assert result.completion_tokens >= 1
            assert result.finish_reason in ("stop", "length")
        asyncio.run(run())
    finally:
        eng.shutdown()


def test_cp_prefill_engine_matches_chunked(tiny_cfg):
    """VERDICT r2 item 5: make_context_parallel_prefill wired into the engine.
    A long prompt served on an sp>1 mesh (ring-attention one-shot prefill +
    cache scatter) must produce the same greedy tokens as the single-device
    chunked-prefill path."""
    from llmlb_tpu.parallel.mesh import MeshConfig

    cfg = tiny_cfg
    rng = np.random.default_rng(3)
    n = 40  # beyond the largest bucket below -> long-prompt path
    prompt = list(rng.integers(1, cfg.vocab_size, size=(n,)))

    def run(mesh_config):
        core = EngineCore(
            cfg, num_slots=2, slot_capacity=128,
            prefill_buckets=(16, 32), seed=0, mesh_config=mesh_config,
        )
        if mesh_config is not None and mesh_config.sp > 1:
            assert core._use_cp_prefill
        core.start()
        try:
            req = Request(
                prompt_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=8),
            )
            core.submit(req)
            toks = []
            while True:
                kind, val = req.events.get(timeout=120)
                if kind == "token":
                    toks.append(val)
                elif kind == "done":
                    break
                else:
                    raise AssertionError(f"engine error: {val}")
            return toks
        finally:
            core.stop()

    chunked = run(None)  # default dp x tp mesh: chunked path
    cp = run(MeshConfig(dp=1, tp=2, sp=4))
    assert chunked == cp, (chunked, cp)


def test_prefill_fairness_round_robin(tiny_cfg):
    """Two long prompts prefill concurrently: the second must start emitting
    before the first finishes its whole decode (no head-of-line blocking)."""
    core = EngineCore(
        tiny_cfg, num_slots=2, slot_capacity=128,
        prefill_buckets=(16,), seed=0,
    )
    core.start()
    try:
        rng = np.random.default_rng(4)
        reqs = [
            Request(
                prompt_ids=list(rng.integers(1, tiny_cfg.vocab_size, size=(48,))),
                sampling=SamplingParams(temperature=0.0, max_tokens=4),
            )
            for _ in range(2)
        ]
        for r in reqs:
            core.submit(r)
        # both must reach their first token; fairness means neither waits for
        # the other's FULL prefill+decode to complete first
        import time as _time

        deadline = _time.monotonic() + 120
        while any(r.first_token_at is None for r in reqs):
            assert _time.monotonic() < deadline, "a prefill starved"
            _time.sleep(0.01)
        # drain
        for r in reqs:
            while True:
                kind, _ = r.events.get(timeout=60)
                if kind in ("done", "error"):
                    break
        gap = abs(reqs[0].first_token_at - reqs[1].first_token_at)
        total = max(r.finished_at for r in reqs) - min(r.submitted_at for r in reqs)
        assert gap < max(0.5 * total, 5.0), (gap, total)
    finally:
        core.stop()
