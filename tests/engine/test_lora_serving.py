"""Multi-LoRA serving (llmlb_tpu/lora, docs/lora.md).

The acceptance invariant: a MIXED-adapter batch (several adapters plus an
adapter-free row) decodes together in single dispatches — no per-adapter
serialization — with each row's output byte-identical to a solo run of that
adapter, greedy and seeded, over both KV layouts; and an engine with LoRA
enabled but unused is bit-identical to a LoRA-free engine (the
test_quantize_off_bit_identical contract, adapter edition).
"""

import asyncio
import threading

import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.lora import save_adapter

CFG = get_preset("debug-tiny")
PROMPT = [3, 5, 7, 9, 11, 2, 4, 6]
ADAPTERS = ("acme", "globex", "initech")


@pytest.fixture(scope="module")
def lora_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("adapters")
    for n in ADAPTERS:
        save_adapter(str(d), n, CFG, rank=4)
    return str(d)


def _drain(request: Request) -> tuple[list[int], str]:
    toks = []
    while True:
        kind, val = request.events.get(timeout=60)
        if kind == "token":
            toks.append(val)
        elif kind == "done":
            return toks, str(val)
        else:
            raise RuntimeError(val)


def _run(core, lora=None, seed=None, temp=0.0, max_tokens=12,
         prompt=PROMPT):
    r = Request(prompt_ids=list(prompt),
                sampling=SamplingParams(temperature=temp, seed=seed,
                                        max_tokens=max_tokens, lora=lora))
    core.submit(r)
    return _drain(r)[0]


def _core(lora_dir=None, *, kv_layout="paged", num_slots=5, **kw):
    return EngineCore(CFG, num_slots=num_slots, slot_capacity=128,
                      prefill_buckets=(8, 16), kv_layout=kv_layout,
                      kv_page_size=16, seed=0, lora_dir=lora_dir, **kw)


@pytest.mark.parametrize("kv_layout", ["paged", "dense"])
def test_mixed_adapter_batch_byte_identical_to_solo(lora_dir, kv_layout):
    """≥3 adapters + 1 adapter-free row decode TOGETHER; every row matches
    its solo run exactly. Greedy and seeded-stochastic (one engine session
    covers both — the jit compiles dominate tier-1 cost), paged and dense.
    """
    core = _core(lora_dir, kv_layout=kv_layout)
    core.start()
    try:
        for kw in ({}, dict(temp=0.8, seed=77)):
            solo = {n: _run(core, n, **kw) for n in (None,) + ADAPTERS}
            # distinct adapters must actually produce distinct streams, or
            # the byte-identity assertions below would be vacuous
            assert len({tuple(v) for v in solo.values()}) == 4

            steps_before = core.metrics.decode_step.n
            results: dict = {}

            def worker(name, kw=kw):
                results[name] = _run(core, name, **kw)

            threads = [threading.Thread(target=worker, args=(n,))
                       for n in (None,) + ADAPTERS]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for name in (None,) + ADAPTERS:
                assert results[name] == solo[name], f"row {name} diverged"
            # decoded together: the step records show 4-wide decode
            # dispatches, and the whole batch took far fewer dispatches
            # than 4 solo runs would (per-adapter serialization would
            # double the step count)
            occupancies = [
                r["active_slots"]
                for r in core.step_stats.snapshot(limit=512)["records"]
                if r["kind"] == "decode"
            ]
            assert max(occupancies, default=0) >= 4, (
                "mixed-adapter batch never decoded 4-wide"
            )
            mixed_steps = core.metrics.decode_step.n - steps_before
            assert mixed_steps <= 20, (
                f"{mixed_steps} decode dispatches for a 12-token 4-row "
                "batch — adapters are being serialized"
            )
    finally:
        core.stop()


def test_lora_enabled_but_unused_bit_identical(lora_dir):
    """The pinned default-off contract, adapter edition: an engine with the
    adapter pool compiled in but NO adapter on any request emits exactly
    the streams a LoRA-free engine does (identity row 0 adds exact 0.0)."""
    plain = _core(None)
    plain.start()
    try:
        ref_greedy = _run(plain, None)
        ref_seeded = _run(plain, None, seed=9, temp=0.9)
    finally:
        plain.stop()
    withlora = _core(lora_dir)
    withlora.start()
    try:
        assert _run(withlora, None) == ref_greedy
        assert _run(withlora, None, seed=9, temp=0.9) == ref_seeded
    finally:
        withlora.stop()


def test_adapter_hot_load_evict_under_pool_pressure(lora_dir):
    """Pool of 2 serving 3 adapters sequentially: the LRU idle adapter
    evicts, the request still serves, and outputs stay solo-identical
    after reload (eviction must not corrupt rows)."""
    core = _core(lora_dir, lora_max_adapters=2)
    core.start()
    try:
        first = _run(core, "acme")
        _run(core, "globex")
        _run(core, "initech")  # evicts one idle adapter
        assert core.metrics.lora_evictions_total >= 1
        assert _run(core, "acme") == first  # reload is exact
        assert core.metrics.lora_loads_total >= 4
    finally:
        core.stop()


def test_prefix_cache_never_shared_across_adapters(lora_dir):
    """Two adapters (and the base model) sharing one prompt must never
    share cached KV: each first use of the prompt under a new adapter is
    a prefix MISS, and outputs stay solo-identical afterward. An
    adapter-blind hit would silently serve adapter A's prompt KV to
    adapter B (the prompt KV depends on wq/wk/wv deltas)."""
    core = _core(lora_dir, min_prefix_len=8)
    core.start()
    prompt = list(range(2, 50))  # long enough to cache (align 16)
    try:
        base_1 = _run(core, None, prompt=prompt)
        hits0 = core.metrics.prefix_hits_total
        base_2 = _run(core, None, prompt=prompt)
        assert core.metrics.prefix_hits_total == hits0 + 1  # warm: base hit
        assert base_2 == base_1

        a_1 = _run(core, "acme", prompt=prompt)
        assert core.metrics.prefix_hits_total == hits0 + 1, (
            "adapter request HIT the base model's cached prompt KV"
        )
        a_2 = _run(core, "acme", prompt=prompt)  # same-adapter reuse is fine
        assert core.metrics.prefix_hits_total == hits0 + 2
        assert a_2 == a_1

        b_1 = _run(core, "globex", prompt=prompt)
        assert core.metrics.prefix_hits_total == hits0 + 2, (
            "adapter B HIT adapter A's (or base) cached prompt KV"
        )
        assert b_1 != a_1  # distinct adapters, distinct continuations
    finally:
        core.stop()


def test_unknown_adapter_rejected_before_slot(lora_dir):
    core = _core(lora_dir)
    try:
        with pytest.raises(ValueError, match="'lora' names unknown adapter"):
            core.submit(Request(prompt_ids=PROMPT,
                                sampling=SamplingParams(lora="nope")))
        with pytest.raises(ValueError, match="not enabled"):
            plain = _core(None)
            try:
                plain.submit(Request(prompt_ids=PROMPT,
                                     sampling=SamplingParams(lora="acme")))
            finally:
                plain.stop()
    finally:
        core.stop()


async def _server_client(engine):
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app

    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    return client


def test_server_surfaces_and_400s(lora_dir):
    """HTTP layer: unknown adapter → 400 naming the field (chat and
    completions), model-suffix selection works, /v1/models advertises the
    lora capability + resident adapters, /metrics renders the lora
    family, /api/health carries the lora block."""
    engine = Engine.from_preset(
        "debug-tiny", num_slots=4, slot_capacity=128,
        prefill_buckets=(8, 16), seed=0, lora_dir=lora_dir,
    )

    async def run():
        client = await _server_client(engine)
        try:
            msgs = [{"role": "user", "content": "hi"}]
            resp = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny", "messages": msgs, "lora": "nope",
                "max_tokens": 4,
            })
            assert resp.status == 400
            body = await resp.json()
            assert "'lora'" in body["error"]["message"]

            resp = await client.post("/v1/completions", json={
                "model": "debug-tiny:nope", "prompt": "hi",
                "max_tokens": 4,
            })
            assert resp.status == 400
            assert "'lora'" in (await resp.json())["error"]["message"]

            # suffix selection serves and differs from the base model
            resp = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny:acme", "messages": msgs,
                "max_tokens": 8, "temperature": 0,
            })
            assert resp.status == 200
            with_adapter = (await resp.json())["choices"][0]["message"]
            resp = await client.post("/v1/chat/completions", json={
                "model": "debug-tiny", "messages": msgs,
                "max_tokens": 8, "temperature": 0,
            })
            base = (await resp.json())["choices"][0]["message"]
            assert with_adapter["content"] != base["content"]

            models = await (await client.get("/v1/models")).json()
            by_id = {m["id"]: m for m in models["data"]}
            assert "lora" in by_id["debug-tiny"]["capabilities"]
            assert "debug-tiny:acme" in by_id  # resident → advertised
            assert by_id["debug-tiny:acme"]["lora"] == "acme"

            health = await (await client.get("/api/health")).json()
            assert health["lora"]["enabled"]
            assert "acme" in health["lora"]["resident"]

            metrics = await (await client.get("/metrics")).text()
            assert "llmlb_engine_lora_loaded 1" in metrics
            assert 'llmlb_engine_lora_requests_total{adapter="acme"}' \
                in metrics
            assert "llmlb_engine_lora_load_seconds_count" in metrics
        finally:
            await client.close()

    try:
        asyncio.run(run())
    finally:
        engine.core.stop()


def test_spec_decode_with_adapter_token_identical(lora_dir):
    """Speculative decoding on: a repetitive prompt drafts n-grams, and the
    adapter stream with spec ON equals the same engine-config stream with
    spec OFF (verify dispatches carry the adapter indices)."""
    prompt = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
    on = _core(lora_dir, spec_decode=True)
    on.start()
    try:
        got_on = _run(on, "acme", prompt=prompt, max_tokens=16)
        assert on.metrics.spec_verify_steps_total > 0
    finally:
        on.stop()
    off = _core(lora_dir, spec_decode=False)
    off.start()
    try:
        got_off = _run(off, "acme", prompt=prompt, max_tokens=16)
    finally:
        off.stop()
    assert got_on == got_off
