"""MoE (Mixtral-class) models through the continuous-batching engine: the
family registry must route the same scheduler loop through mixtral's serving
fns with the experts sharded over the default ep mesh axis."""

import asyncio

import pytest

from llmlb_tpu.engine.scheduler import SamplingParams
from llmlb_tpu.engine.service import Engine


@pytest.fixture(scope="module")
def moe_engine():
    eng = Engine.from_preset(
        "debug-moe-tiny", num_slots=4, slot_capacity=64,
        prefill_buckets=(16, 32), seed=0,
    )
    yield eng
    eng.shutdown()


def test_moe_engine_uses_mixtral_family(moe_engine):
    from llmlb_tpu.models import mixtral

    assert moe_engine.core.family is mixtral
    # default mesh gives the expert dim its gcd share of devices (8 devs, 4 experts)
    assert moe_engine.core.mesh.shape["ep"] == 4


def test_moe_complete_deterministic(moe_engine):
    async def run():
        ids = moe_engine.tokenizer.encode("expert routing")
        a = await moe_engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        b = await moe_engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=8))
        assert a.text == b.text
        assert a.completion_tokens == b.completion_tokens
    asyncio.run(run())


def test_moe_embeddings_rejected_as_client_error(moe_engine):
    async def run():
        with pytest.raises(ValueError, match="not supported"):
            await moe_engine.embed([[1, 2, 3]])
    asyncio.run(run())


def test_moe_concurrent_requests_complete(moe_engine):
    async def run():
        ids = moe_engine.tokenizer.encode("hello")
        outs = await asyncio.gather(*[
            moe_engine.complete(ids, SamplingParams(temperature=0.0, max_tokens=6))
            for _ in range(6)
        ])
        for o in outs:
            assert o.completion_tokens > 0
    asyncio.run(run())
