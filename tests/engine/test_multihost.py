"""Multi-host serving path: jax.distributed over 2 simulated hosts.

VERDICT r2 item 8: a DCN-aware mesh (dp across hosts, ep/tp inside) running
the Mixtral-class sharded step across a real 2-process jax.distributed
cluster (CPU simulation; process boundary = DCN slice).
"""

import pytest

from llmlb_tpu.parallel.distributed import build_hybrid_mesh, run_multihost_selftest
from llmlb_tpu.parallel.mesh import MeshConfig


def _selftest_or_skip(**kwargs):
    """Environment gate: some jaxlib builds cannot run cross-process
    collectives on the CPU backend at all (multihost_utils raises
    INVALID_ARGUMENT inside the worker). Skip on exactly that signature so
    every other worker failure still fails the test."""
    try:
        return run_multihost_selftest(**kwargs)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
        raise


def test_hybrid_mesh_single_slice_axes():
    """Degenerate cluster (one slice): the helper still yields the standard
    (dp, sp, ep, tp) axis layout. Multi-slice DCN splits require >=2
    processes/slices and are covered by the spawned 2-host test below."""
    mesh = build_hybrid_mesh(MeshConfig(dp=2, ep=2, tp=2), dcn_dp=1)
    assert dict(mesh.shape) == {"dp": 2, "sp": 1, "ep": 2, "tp": 2}


def test_two_host_cluster_runs_sharded_moe_step():
    _selftest_or_skip(num_hosts=2, devices_per_host=4)


def test_lockstep_engine_across_two_hosts_matches_single_host():
    """Full serving loop across a 2-process cluster: the leader's tick-plan
    broadcast keeps followers dispatching identical collectives; greedy
    tokens must equal a single-host engine with the same seed/config."""
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.engine.scheduler import EngineCore
    from llmlb_tpu.parallel.distributed import collect_tokens, selftest_requests

    # single-host baseline: the SAME request builder the distributed worker
    # uses, so the equivalence is structural
    cfg = get_preset("debug-tiny")
    core = EngineCore(cfg, num_slots=2, slot_capacity=64,
                      prefill_buckets=(16,), seed=0)
    core.start()
    try:
        reqs = selftest_requests(cfg)
        for r in reqs:
            core.submit(r)
        baseline = collect_tokens(reqs)
    finally:
        core.stop()

    distributed = _selftest_or_skip(
        num_hosts=2, devices_per_host=4, mode="--engine-worker"
    )
    assert distributed == baseline, (distributed, baseline)
