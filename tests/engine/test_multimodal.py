"""Multimodal engines (whisper ASR, TTS, diffusion images) + their HTTP
routes: model-level correctness properties and OpenAI-contract responses.
Strategy per SURVEY.md §4: tiny random-weight configs, in-process servers."""

import asyncio
import base64
import io
import wave
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.engine.asr import AsrEngine, decode_wav, resample_linear
from llmlb_tpu.engine.image import ImageEngine, encode_png
from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.engine.tts import TtsEngine, encode_wav


# ---------------------------------------------------------------------- audio


def _tone(freq=440.0, seconds=0.3, rate=16000):
    t = np.arange(int(seconds * rate)) / rate
    return (0.5 * np.sin(2 * np.pi * freq * t)).astype(np.float32)


def test_wav_roundtrip():
    audio = _tone()
    data = encode_wav(audio)
    decoded, rate = decode_wav(data)
    assert rate == 16000
    np.testing.assert_allclose(decoded, audio, atol=1e-3)


def test_decode_wav_rejects_garbage_as_client_error():
    with pytest.raises(ValueError, match="WAV"):
        decode_wav(b"ID3\x04not audio at all" * 10)


def test_transcriptions_route_400_on_bad_audio():
    async def run():
        import aiohttp

        eng = Engine.from_preset(
            "debug-tiny", num_slots=1, slot_capacity=32, prefill_buckets=(16,),
        )
        try:
            client = await _mm_client(eng, asr=AsrEngine.from_random(seed=9))
            form = aiohttp.FormData()
            form.add_field("file", b"not-a-wav", filename="x.mp3",
                           content_type="audio/mpeg")
            r = await client.post("/v1/audio/transcriptions", data=form)
            assert r.status == 400
            body = await r.json()
            assert "decode" in body["error"]["message"]
            await client.close()
        finally:
            eng.shutdown()
    asyncio.run(run())


def test_resample_halves_length():
    audio = _tone(rate=32000)
    out = resample_linear(audio, 32000, 16000)
    assert abs(len(out) - len(audio) // 2) <= 1


def test_mel_spectrogram_shape_and_finiteness():
    from llmlb_tpu.models.whisper import HOP_LENGTH, log_mel_spectrogram

    audio = _tone(seconds=0.5)
    mel = np.asarray(log_mel_spectrogram(jnp.asarray(audio)))
    assert mel.shape[1] == 80
    assert abs(mel.shape[0] - len(audio) // HOP_LENGTH) <= 2
    assert np.isfinite(mel).all()


def test_whisper_decoder_causality():
    """Changing a future token must not affect earlier positions' logits."""
    from llmlb_tpu.models import whisper

    eng = AsrEngine.from_random(seed=1)
    cfg, params = eng.cfg, eng.params
    mel = jnp.zeros((1, 32, cfg.n_mels), jnp.float32)
    enc = whisper.encode_audio(params, cfg, mel)
    toks = jnp.asarray([[cfg.sot_token, 5, 7, 9]], jnp.int32)
    toks2 = toks.at[0, 3].set(11)
    la = np.asarray(whisper.decoder_logits(params, cfg, toks, enc))
    lb = np.asarray(whisper.decoder_logits(params, cfg, toks2, enc))
    np.testing.assert_allclose(la[0, :3], lb[0, :3], rtol=1e-5, atol=1e-5)
    assert not np.allclose(la[0, 3], lb[0, 3])


def test_asr_transcribe_deterministic():
    eng = AsrEngine.from_random(seed=2)
    wav = encode_wav(_tone(seconds=0.2))
    a = eng.transcribe_wav_bytes(wav, max_tokens=6)
    b = eng.transcribe_wav_bytes(wav, max_tokens=6)
    assert a == b  # greedy decode is deterministic


def test_tts_produces_audio_and_respects_speed():
    eng = TtsEngine.from_random(seed=3)
    wav = eng.synthesize("hello world", voice="alloy")
    audio, rate = decode_wav(wav)
    assert rate == 16000
    assert len(audio) > 1000
    assert np.isfinite(audio).all()
    fast = eng.synthesize("hello world", voice="alloy", speed=2.0)
    fast_audio, _ = decode_wav(fast)
    assert abs(len(fast_audio) - len(audio) / 2) < 0.1 * len(audio)


def test_tts_voice_changes_output():
    eng = TtsEngine.from_random(seed=3)
    a, _ = decode_wav(eng.synthesize("same text", voice="alloy"))
    b, _ = decode_wav(eng.synthesize("same text", voice="echo"))
    assert not np.allclose(a, b)


def test_tts_validation():
    eng = TtsEngine.from_random(seed=3)
    with pytest.raises(ValueError):
        eng.synthesize("")
    with pytest.raises(ValueError):
        eng.synthesize("x", speed=9.0)


def test_tts_checkpoint_roundtrip(tmp_path):
    from llmlb_tpu.models import tts as tts_model

    eng = TtsEngine.from_random(seed=4)
    tts_model.save_checkpoint(str(tmp_path / "tts"), eng.cfg, eng.params)
    cfg2, params2 = tts_model.load_checkpoint(str(tmp_path / "tts"))
    assert cfg2 == eng.cfg
    for k in eng.params:
        a = jax.tree.leaves(eng.params[k])
        b = jax.tree.leaves(params2[k])
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------- images


def test_png_encoder_valid():
    rgb = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(16, 16, 3)
    png = encode_png(rgb)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    # decode IDAT back and compare pixels (filter byte 0 per row)
    idat_start = png.index(b"IDAT") + 4
    idat_len = int.from_bytes(png[idat_start - 8: idat_start - 4], "big")
    raw = zlib.decompress(png[idat_start: idat_start + idat_len])
    rows = [raw[i * (1 + 48) + 1: (i + 1) * (1 + 48)] for i in range(16)]
    np.testing.assert_array_equal(
        np.frombuffer(b"".join(rows), np.uint8).reshape(16, 16, 3), rgb
    )


def test_image_generate_shapes_and_determinism():
    eng = ImageEngine.from_random(seed=5, sample_steps=4)
    a = eng.generate("a red square", n=2, seed=7)
    b = eng.generate("a red square", n=2, seed=7)
    assert len(a) == 2
    assert a[0] == b[0]  # same seed -> same image
    c = eng.generate("a red square", n=1, seed=8)
    assert c[0] != a[0]  # different seed -> different image


def test_image_prompt_conditioning_changes_output():
    eng = ImageEngine.from_random(seed=5, sample_steps=4)
    a = eng.generate("a cat", n=1, seed=3)
    b = eng.generate("a dog", n=1, seed=3)
    assert a[0] != b[0]


def test_diffusion_checkpoint_roundtrip(tmp_path):
    from llmlb_tpu.models import diffusion

    eng = ImageEngine.from_random(seed=6, sample_steps=2)
    diffusion.save_checkpoint(str(tmp_path / "diff"), eng.cfg, eng.params)
    cfg2, params2 = diffusion.load_checkpoint(str(tmp_path / "diff"))
    assert cfg2 == eng.cfg
    for a, b in zip(jax.tree.leaves(eng.params), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------------ HTTP routes


@pytest.fixture(scope="module")
def mm_engine():
    eng = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64,
        prefill_buckets=(16, 32), seed=0,
    )
    yield eng
    eng.shutdown()


async def _mm_client(engine, **services) -> TestClient:
    client = TestClient(TestServer(
        create_engine_app(engine, owns_engine=False, **services)
    ))
    await client.start_server()
    return client


def test_routes_404_when_service_absent(mm_engine):
    async def run():
        client = await _mm_client(mm_engine)
        try:
            r = await client.post("/v1/audio/speech", json={"input": "x"})
            assert r.status == 404
            r = await client.post("/v1/images/generations", json={"prompt": "x"})
            assert r.status == 404
        finally:
            await client.close()
    asyncio.run(run())


def test_full_multimodal_server(mm_engine):
    async def run():
        asr = AsrEngine.from_random(seed=1)
        tts = TtsEngine.from_random(seed=2)
        image = ImageEngine.from_random(seed=3, sample_steps=2)
        client = await _mm_client(mm_engine, asr=asr, tts=tts, image=image)
        try:
            # /v1/models lists all four with capabilities
            r = await client.get("/v1/models")
            body = await r.json()
            caps = {m["id"]: m["capabilities"] for m in body["data"]}
            assert caps[asr.model_id] == ["audio_transcription"]
            assert caps[tts.model_id] == ["audio_speech"]
            assert caps[image.model_id] == ["image_generation"]

            # speech -> wav
            r = await client.post("/v1/audio/speech", json={
                "input": "hi", "voice": "nova"})
            assert r.status == 200
            assert r.content_type == "audio/wav"
            wav = await r.read()
            with wave.open(io.BytesIO(wav), "rb") as wf:
                assert wf.getframerate() == 16000

            # transcription accepts that wav back (multipart)
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("file", wav, filename="a.wav",
                           content_type="audio/wav")
            form.add_field("model", asr.model_id)
            r = await client.post("/v1/audio/transcriptions", data=form)
            assert r.status == 200
            assert "text" in await r.json()

            # images
            r = await client.post("/v1/images/generations", json={
                "prompt": "a tiny square", "n": 1})
            assert r.status == 200
            data = (await r.json())["data"]
            png = base64.b64decode(data[0]["b64_json"])
            assert png[:8] == b"\x89PNG\r\n\x1a\n"

            # validation errors
            r = await client.post("/v1/images/generations", json={"n": 1})
            assert r.status == 400
            r = await client.post("/v1/audio/speech", json={})
            assert r.status == 400
        finally:
            await client.close()
    asyncio.run(run())
