"""Paged KV cache: PagePool bookkeeping, scheduler edge cases, zero-copy.

Three layers, mirroring the implementation split:
- PagePool unit tests (pure host-side: alloc/ref/unref free-list math,
  double-free detection, trash-page reservation).
- EngineCore integration (CPU backend): pool exhaustion at insert queues
  requests instead of crashing, exhaustion mid-decode evicts prefix pages
  then degrades to an early 'length' finish, cancellation releases pages,
  and the engine keeps serving after every one of those paths.
- The zero-copy guarantee: a prefix-cache hit in paged mode dispatches NO
  device-side cache copy (kv_copy_dispatches stays 0) — the paged
  counterpart of test_prefix_cache's no-re-prefill guard.
"""

import queue

import numpy as np
import pytest

from llmlb_tpu.engine.paging import PageError, PagePool
from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams

# ------------------------------------------------------------------ page pool


def test_alloc_is_all_or_nothing():
    pool = PagePool(6)  # 5 usable (page 0 reserved)
    assert pool.total == 5
    got = pool.alloc(3)
    assert got is not None and len(got) == 3
    assert pool.available() == 2
    assert pool.alloc(3) is None  # only 2 free: nothing handed out
    assert pool.available() == 2
    assert pool.alloc(2) is not None
    assert pool.available() == 0
    assert pool.alloc(0) == []


def test_unref_returns_page_and_double_free_raises():
    pool = PagePool(4)
    (page,) = pool.alloc(1)
    pool.unref(page)
    assert pool.available() == 3
    with pytest.raises(PageError):
        pool.unref(page)  # double free must never silently pass


def test_ref_shares_ownership():
    pool = PagePool(4)
    (page,) = pool.alloc(1)
    pool.ref(page)  # second owner (prefix cache / sharing slot)
    pool.unref(page)
    assert pool.available() == 2  # still held by the other owner
    pool.unref(page)
    assert pool.available() == 3
    with pytest.raises(PageError):
        pool.ref(page)  # a free page has no owners to join


def test_reserved_trash_page_is_untouchable():
    pool = PagePool(4)
    pages = pool.alloc(3)
    assert 0 not in pages  # page 0 never allocated
    with pytest.raises(PageError):
        pool.unref(0)
    with pytest.raises(PageError):
        pool.unref(99)


def test_reset_reclaims_everything():
    pool = PagePool(5)
    pool.alloc(4)
    pool.reset()
    assert pool.available() == 4
    assert pool.refcount(0) == 1  # trash page stays pinned


# ---------------------------------------------------------------- engine core


def _req(prompt, max_tokens=4, temperature=0.0):
    return Request(prompt_ids=list(prompt),
                   sampling=SamplingParams(temperature=temperature,
                                           max_tokens=max_tokens))


def _collect(request, timeout=120):
    toks = []
    while True:
        kind, value = request.events.get(timeout=timeout)
        if kind == "token":
            toks.append(value)
        elif kind == "error":
            raise AssertionError(f"engine error: {value}")
        else:
            return toks, value


def _core(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("slot_capacity", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("seed", 0)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_page_size", 16)
    return EngineCore(get_preset("debug-tiny"), **kw)


@pytest.fixture(scope="module")
def prompt():
    rng = np.random.default_rng(11)
    cfg = get_preset("debug-tiny")
    return list(rng.integers(1, cfg.vocab_size, size=(48,)))


def test_prefix_hit_is_zero_copy(prompt):
    """Acceptance guard: a paged-mode hit writes donor page ids into the new
    slot's block table — no device cache-copy dispatch, ever."""
    core = _core()
    core.start()
    try:
        _collect(core.submit(_req(prompt)))
        _collect(core.submit(_req(prompt)))
        assert core.metrics.prefix_hits_total == 1
        assert core.metrics.prefix_cached_tokens_total == 32
        assert core.kv_copy_dispatches == 0, (
            "paged prefix hit dispatched a device cache copy"
        )
    finally:
        core.stop()


def test_donor_slot_frees_immediately_in_paged_mode(prompt):
    """The occupancy win: donating a prefix pins PAGES, not the slot — every
    slot returns to the serving pool on completion."""
    core = _core(num_slots=2, prefix_cache_slots=1)
    core.start()
    try:
        _collect(core.submit(_req(prompt)))
        assert len(core.prefix_cache) == 1
        assert core.prefix_cache.pinned_slots() == frozenset()
        assert len(core._free_slots()) == 2  # both slots serve traffic
        info = core.prefix_cache_info()
        assert info["pinned_slots"] == 0
        assert info["pinned_pages"] == 3  # 48-token head / 16-token pages
    finally:
        core.stop()


def test_pool_exhaustion_at_insert_queues_request():
    """More concurrent prompts than the pool covers: the overflow request
    waits (held on the pool) and completes once pages free — never an error,
    never a crash."""
    cfg = get_preset("debug-tiny")
    rng = np.random.default_rng(3)
    # 4 slots but only ~2 requests' worth of pages: 2 pages per 20-token
    # prompt (+1 page of decode growth), 5 usable pages in the pool
    core = _core(num_slots=4, kv_pages=6, prefix_cache=False)
    core.start()
    try:
        reqs = [_req(rng.integers(1, cfg.vocab_size, size=(20,)), max_tokens=4)
                for _ in range(6)]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            _toks, finish = _collect(r)
            assert finish in ("stop", "length")
        # pool fully reclaimed once everything finished
        assert core.page_pool.available() == core.page_pool.total
    finally:
        core.stop()


def test_pool_exhaustion_mid_decode_finishes_early_and_keeps_serving():
    """Decode growth that the pool cannot cover finishes that request with
    'length' instead of crashing the step loop, and the engine serves new
    requests afterwards."""
    core = _core(num_slots=2, kv_pages=5, prefix_cache=False)
    core.start()
    try:
        # two growing requests race for 4 usable pages; each wants
        # ceil((8 + 40)/16) = 3 — at least one must be cut short
        a = core.submit(_req([3] * 8, max_tokens=40))
        b = core.submit(_req([5] * 8, max_tokens=40))
        toks_a, fin_a = _collect(a)
        toks_b, fin_b = _collect(b)
        assert {fin_a, fin_b} <= {"stop", "length"}
        assert len(toks_a) >= 1 and len(toks_b) >= 1
        # the loop survived: a fresh request still completes
        toks_c, fin_c = _collect(core.submit(_req([7] * 8, max_tokens=4)))
        assert fin_c in ("stop", "length")
        assert core.page_pool.available() == core.page_pool.total
    finally:
        core.stop()


def test_cancel_releases_pages(prompt):
    """Client cancel mid-suffix-prefill returns every page the request held
    (shared prefix pages drop to the donor's refcount, fresh ones free).
    Driven inline so the cancellation lands between insert and the first
    suffix chunk."""
    core = _core()
    # warm the cache: one completed request donates its prompt head
    warm = _req(prompt, max_tokens=2)
    core.pending.put(warm)
    for _ in range(500):
        core._try_insert()
        core._advance_prefill()
        core._decode_active()
        try:
            if warm.events.get_nowait()[0] == "done":
                break
        except queue.Empty:
            pass
    assert len(core.prefix_cache) == 1
    pinned = core._prefix_pinned_pages
    used_before = core.page_pool.used()
    assert used_before == pinned  # only the donated pages are held

    r = _req(prompt, max_tokens=8)
    core.pending.put(r)
    core._try_insert()  # zero-copy hit: shares 2 pages, allocs the rest
    assert core.metrics.prefix_hits_total == 1
    assert core.page_pool.used() > used_before
    r.cancel()
    core._advance_prefill()  # observes the cancellation
    assert r.events.get_nowait() == ("done", "cancelled")
    assert core.page_pool.used() == used_before  # every page returned
    (entry,) = core.prefix_cache.entries()
    assert entry.refcount == 0  # reader released the donor entry too


def test_hit_under_pool_pressure_never_evicts_its_own_donor(prompt):
    """Regression: reserving suffix pages for a hit must not LRU-evict the
    matched donor itself — that would free (and possibly recycle as 'fresh')
    the very pages the hit is about to share. The donor is pinned across the
    reservation, so the request waits on the pool instead."""
    core = _core(num_slots=4, kv_pages=7)  # 6 usable pages
    # donor: 48-token prompt -> 3 pages pinned, 3 free
    warm = _req(prompt, max_tokens=2)
    core.pending.put(warm)
    for _ in range(500):
        core._try_insert()
        core._advance_prefill()
        core._decode_active()
        try:
            if warm.events.get_nowait()[0] == "done":
                break
        except queue.Empty:
            pass
    assert core._prefix_pinned_pages == 3
    (donor,) = core.prefix_cache.entries()

    # occupy the 3 free pages with a request that stays active (max_tokens
    # keeps it within 3 pages, so its own decode growth never needs a 4th —
    # the only eviction pressure in play is the hit's reservation)
    blocker = _req([p + 1 for p in prompt[:33]], max_tokens=8)
    core.pending.put(blocker)
    core._try_insert()
    assert core.page_pool.available() == 0

    # a hit on the donor needs 1 fresh page; the only refcount-0 entry is
    # the donor itself — it must NOT be sacrificed to serve its own hit
    r = _req(prompt, max_tokens=2)
    core.pending.put(r)
    core._try_insert()
    assert core._held_request is r  # parked on the pool, not inserted
    assert core.prefix_cache.entries(), "donor was evicted to serve its hit"
    assert donor.refcount == 0  # the pin did not leak past the attempt

    # once the blocker finishes, pages free and the held hit completes
    for _ in range(2000):
        core._try_insert()
        core._advance_prefill()
        core._decode_active()
        try:
            kind, value = r.events.get_nowait()
            if kind == "done":
                break
            assert kind == "token"
        except queue.Empty:
            pass
    else:
        raise AssertionError("held hit never completed")
    assert core.metrics.prefix_hits_total == 1


def test_pool_pressure_evicts_prefix_pages(prompt):
    """A new request that the free pages cannot cover reclaims prefix-cache
    pages LRU before queueing — cached history never starves live traffic."""
    core = _core(num_slots=2, kv_pages=9, prefix_cache_slots=2)
    core.start()
    try:
        _collect(core.submit(_req(prompt)))  # donates 3 pages of 8 usable
        assert core._prefix_pinned_pages == 3
        # a fat prompt wants 4 pages; free = 8 - 3 pinned = 5 — fits without
        # eviction. Follow with another: 5 - 4 = 1 free, next wants 4 ->
        # must evict the donor's 3 pages.
        other = [p + 1 for p in prompt]  # no shared prefix
        third = [p + 2 for p in prompt]
        a = core.submit(_req(other[:47], max_tokens=2))
        b = core.submit(_req(third[:47], max_tokens=2))
        _collect(a)
        _collect(b)
        assert core.metrics.prefix_evictions_total >= 1
    finally:
        core.stop()


def test_paged_gauges_in_metrics_and_system(prompt):
    core = _core()
    core.start()
    try:
        _collect(core.submit(_req(prompt)))
        info = core.kv_cache_info()
        assert info["layout"] == "paged"
        assert info["pages_total"] == 4 * 4  # 4 slots x 4 pages/slot
        assert info["pages_pinned"] == 3
        assert 0.0 <= info["fragmentation"] <= 1.0
        stats = core.stats()
        text = core.metrics.render(
            queue_depth=stats.queued, active_slots=stats.active_slots,
            num_slots=stats.num_slots, prefix_cache=core.prefix_cache_info(),
            kv_cache=info,
        )
        for name in ("llmlb_engine_kv_pages_total", "llmlb_engine_kv_pages_free",
                     "llmlb_engine_kv_pages_pinned",
                     "llmlb_engine_kv_page_fragmentation_ratio",
                     "llmlb_engine_kv_pool_utilization_ratio",
                     "llmlb_engine_kv_page_waste_tokens_mean"):
            assert name in text, name
    finally:
        core.stop()


def test_paged_goldens_identical_with_quantize_off(prompt):
    """Golden run over the quantization knob: an explicit quantize="off"
    engine produces the exact token streams (greedy AND seeded stochastic)
    and the exact kv gauges the default engine does — the int8 plumbing is
    provably zero-cost when disabled (docs/quantization.md)."""
    results = {}
    for quantize in (None, "off"):
        core = _core(quantize=quantize)
        core.start()
        try:
            greedy = _req(prompt, max_tokens=8)
            seeded = Request(prompt_ids=list(prompt),
                            sampling=SamplingParams(temperature=0.9,
                                                    max_tokens=8, seed=5))
            core.submit(greedy)
            core.submit(seeded)
            toks_g, _ = _collect(greedy)
            toks_s, _ = _collect(seeded)
            results[quantize] = (toks_g, toks_s, core.kv_cache_info())
        finally:
            core.stop()
    assert results[None] == results["off"]


def test_dense_layout_reports_dense_info():
    core = _core(kv_layout="dense")
    try:
        assert core.page_pool is None
        info = core.kv_cache_info()
        assert info["layout"] == "dense"
        assert info["hbm_bytes"] > 0
    finally:
        core.stop()


def test_env_var_selects_layout(monkeypatch):
    monkeypatch.setenv("LLMLB_KV_LAYOUT", "dense")
    core = EngineCore(get_preset("debug-tiny"), num_slots=2,
                      slot_capacity=64, prefill_buckets=(16,), seed=0)
    assert core.kv_layout == "dense" and core.page_pool is None
    core.stop()
    monkeypatch.delenv("LLMLB_KV_LAYOUT")
    core = EngineCore(get_preset("debug-tiny"), num_slots=2,
                      slot_capacity=64, prefill_buckets=(16,), seed=0)
    assert core.kv_layout == "paged" and core.page_pool is not None
    core.stop()
