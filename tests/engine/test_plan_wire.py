"""Multihost plan-wire round trip: every SamplingParams field must survive
serialization, so forgetting a field when adding a knob is a test failure
instead of a silent multihost divergence (PR 5 shipped `constraint` over the
wire by hand; `speculative` and whatever comes next ride the same check).

The wire form is what EngineCore._plan_wire emits (leader) and what
_apply_plan reconstructs (followers): dataclasses.asdict(sampling) →
SamplingParams(**payload). These tests exercise exactly those two functions
and synthesize a distinctive non-default value for EVERY declared field —
a new field is covered the moment it is declared."""

import dataclasses
import pickle

from llmlb_tpu.engine.scheduler import Request, SamplingParams


def _distinct_value(field: dataclasses.Field):
    """A JSON-safe value distinguishable from the field's default, derived
    from the annotation so newly added fields get covered automatically."""
    ann = str(field.type)
    if "dict" in ann:
        return {"probe": field.name, "n": 3}
    if "bool" in ann:
        default = field.default
        return not default if isinstance(default, bool) else True
    if "float" in ann:
        return 0.125
    if "int" in ann:
        return 7
    if "str" in ann:
        return f"probe-{field.name}"
    raise AssertionError(
        f"SamplingParams.{field.name}: add a wire-probe rule for {ann!r} "
        "(and make sure the field is JSON-safe for the plan broadcast)"
    )


def _probe_params() -> SamplingParams:
    values = {
        f.name: _distinct_value(f) for f in dataclasses.fields(SamplingParams)
    }
    return SamplingParams(**values)


def _wire_roundtrip(request: Request) -> Request:
    """The exact leader→follower path: _plan_wire's payload shape, through
    pickle (the multihost broadcast encoding), back via _apply_plan's
    constructor call."""
    payload = {
        "request_id": request.request_id,
        "prompt_ids": list(request.prompt_ids),
        "sampling": dataclasses.asdict(request.sampling),
    }
    payload = pickle.loads(pickle.dumps(payload))
    return Request(
        prompt_ids=payload["prompt_ids"],
        sampling=SamplingParams(**payload["sampling"]),
        request_id=payload["request_id"],
    )


def test_every_sampling_field_survives_the_wire():
    params = _probe_params()
    shadow = _wire_roundtrip(
        Request(prompt_ids=[1, 2, 3], sampling=params)
    ).sampling
    for f in dataclasses.fields(SamplingParams):
        assert getattr(shadow, f.name) == getattr(params, f.name), (
            f"SamplingParams.{f.name} was lost or mangled on the plan wire"
        )


def test_probe_values_differ_from_defaults():
    """The round-trip assertion above is only meaningful if the probe value
    actually differs from the default (a dropped field that deserializes to
    its default must FAIL the wire test)."""
    params = _probe_params()
    defaults = SamplingParams()
    for f in dataclasses.fields(SamplingParams):
        assert getattr(params, f.name) != getattr(defaults, f.name), (
            f"probe for SamplingParams.{f.name} equals its default; "
            "_distinct_value needs a better rule"
        )


def test_speculative_and_constraint_ride_the_wire_verbatim():
    params = SamplingParams(
        constraint={"type": "json_object"},
        speculative={"enabled": True, "max_draft_tokens": 6},
    )
    shadow = _wire_roundtrip(
        Request(prompt_ids=[5], sampling=params)
    ).sampling
    assert shadow.constraint == {"type": "json_object"}
    assert shadow.speculative == {"enabled": True, "max_draft_tokens": 6}


def test_plan_wire_matches_engine_implementation():
    """Guard against _plan_wire/_apply_plan drifting from the shape this
    test assumes: the real methods run against a core-free stub (they touch
    no device state for the serialization itself)."""
    from llmlb_tpu.engine.scheduler import EngineCore

    req = Request(prompt_ids=[1, 2], sampling=_probe_params())
    plan = {"new": [req], "cancelled": [], "stop": False}
    wire = EngineCore._plan_wire(None, plan)  # self unused in _plan_wire
    assert wire["new"][0]["sampling"] == dataclasses.asdict(req.sampling)
    rebuilt = SamplingParams(**wire["new"][0]["sampling"])
    assert rebuilt == req.sampling
