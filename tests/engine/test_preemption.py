"""Overload protection: priority classes, preemption park/resume identity,
chunked-prefill decode budget, deadline shedding (docs/scheduling.md).

The load-bearing guarantee is BIT-IDENTITY: a preempted request — parked
under pressure (pages released, grammar cursor and drafter retained) and
resumed later via a chunk-prefill of its committed tokens — must emit
exactly the token stream an uninterrupted run would have. Greedy is
deterministic outright; seeded stochastic holds because sample keys fold
PRNGKey(seed) by ABSOLUTE position, independent of batch composition.
Covered over paged and dense KV layouts and with speculative decoding on.
"""

import asyncio
import json
import time

import jsonschema
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams
from llmlb_tpu.engine.service import Engine

# Every value is bounded (enum, not bare integer: unbounded digit runs
# would let greedy emit digits past max_tokens and length-cut the JSON),
# so the grammar must reach its accepting state and force EOS.
SCHEMA = {
    "type": "object",
    "properties": {"name": {"type": "string", "maxLength": 8},
                   "n": {"enum": [0, 1, 2, 3]}},
    "required": ["name", "n"],
}


# One DECODING slot: the victim owns it, so a high-priority arrival MUST
# preempt — no scheduling ambiguity about which slot parks. The split
# params run the same suite over the disaggregated engine (PR 11): one
# prefill slot + one decode slot, so the interloper's handoff adoption is
# the preemption point — the victim parks MID-GENERATION, resumes through
# the prefill pool, and hands off a second time. Bit-identity must hold
# across park + double handoff, grammar cursor and drafter riding along.
@pytest.fixture(scope="module",
                params=["paged", "dense", "paged-spec", "split",
                        "split-spec"])
def engine(request):
    layout = "dense" if request.param == "dense" else "paged"
    extra = {}
    if request.param.endswith("spec"):
        extra["spec_decode"] = True
    if request.param.startswith("split"):
        extra["role"] = "split"
        extra["disagg_prefill_slots"] = 1
    slots = 2 if request.param.startswith("split") else 1
    eng = Engine.from_preset(
        "debug-tiny", num_slots=slots, slot_capacity=128,
        prefill_buckets=(16, 32), seed=0,
        kv_layout=layout, kv_page_size=16, **extra,
    )
    yield eng
    eng.shutdown()


async def _consume(agen, out: list):
    async for delta in agen:
        out.append(delta)


async def _wait_for_text(out: list, min_chars: int, timeout=60.0):
    # generous: on a contended 2-core CPU host the first dispatch of a
    # fresh engine may sit behind a multi-10s XLA compile; the poll costs
    # nothing when healthy
    deadline = time.monotonic() + timeout
    while sum(len(d.text) for d in out) < min_chars:
        assert time.monotonic() < deadline, "victim stream produced no text"
        await asyncio.sleep(0.005)


def _text(out: list) -> str:
    return "".join(d.text for d in out)


async def _preempt_roundtrip(eng, victim_params: SamplingParams,
                             prompt="the quick brown fox jumps over"):
    """Run the victim alone (reference), then again with a high-priority
    interloper forcing a park/resume; return (reference_text, victim_text,
    preemptions_delta)."""
    ids = eng.tokenizer.encode(prompt)
    ref = await eng.complete(ids, victim_params)

    before = eng.core.metrics.preemptions_total
    out: list = []
    task = asyncio.create_task(
        _consume(eng.stream(ids, victim_params), out)
    )
    await _wait_for_text(out, 2)  # decoding, past first_pending
    hi = await eng.complete(
        eng.tokenizer.encode("interloper"),
        SamplingParams(temperature=0.0, max_tokens=6, priority=0),
    )
    assert hi.finish_reason in ("stop", "length")
    await task
    return ref.text, _text(out), eng.core.metrics.preemptions_total - before


def test_park_resume_greedy_token_identity(engine):
    async def run():
        ref, got, preempted = await _preempt_roundtrip(
            engine, SamplingParams(temperature=0.0, max_tokens=48,
                                   priority=2),
        )
        assert preempted >= 1, "high-priority arrival did not preempt"
        assert got == ref
        assert engine.core.metrics.preempt_resumes_total >= 1
    asyncio.run(run())


def test_park_resume_seeded_stochastic_identity(engine):
    async def run():
        ref, got, preempted = await _preempt_roundtrip(
            engine, SamplingParams(temperature=0.9, seed=1234,
                                   max_tokens=48, priority=2),
        )
        assert preempted >= 1
        assert got == ref
    asyncio.run(run())


def test_constraint_cursor_parks_and_resumes(engine):
    """ROADMAP 2c residual: a parked constrained slot's ConstraintState
    cursor must park and resume WITH the request — a re-walk from the FSM
    start state would emit a second JSON document opener mid-stream."""
    async def run():
        params = SamplingParams(
            temperature=0.0, max_tokens=96, priority=2,
            constraint={"type": "json_schema", "schema": SCHEMA},
        )
        violations_before = engine.core.metrics.constraint_violations_total
        ref, got, preempted = await _preempt_roundtrip(engine, params)
        assert preempted >= 1
        assert got == ref
        jsonschema.validate(json.loads(got), SCHEMA)
        assert (engine.core.metrics.constraint_violations_total
                == violations_before)
    asyncio.run(run())


def test_midstream_page_exhaustion_parks_instead_of_finishing():
    """A tiny page pool forced mid-decode exhaustion to finish requests at
    'length' pre-preemption; now the loser parks and resumes, completing
    token-identical to an uncontended run."""
    eng = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64,
        prefill_buckets=(16,), seed=0, kv_layout="paged", kv_page_size=8,
        kv_pages=9,  # trash page + 8: two growing decoders cannot both fit
        prefix_cache=False,
    )
    try:
        async def run():
            params = SamplingParams(temperature=0.0, max_tokens=24)
            a_ids = eng.tokenizer.encode("alpha alpha")
            b_ids = eng.tokenizer.encode("beta beta")
            ref_a = await eng.complete(a_ids, params)
            ref_b = await eng.complete(b_ids, params)
            got_a, got_b = await asyncio.gather(
                eng.complete(a_ids, params), eng.complete(b_ids, params)
            )
            assert got_a.text == ref_a.text
            assert got_b.text == ref_b.text
            assert got_a.finish_reason == ref_a.finish_reason
            assert got_b.finish_reason == ref_b.finish_reason
        asyncio.run(run())
    finally:
        eng.shutdown()


def test_prefill_chunk_budget_interleaves_and_is_token_identical():
    """With the budget on and a decoder active, a one-shot-sized prompt
    runs as multiple budget-sized chunks (decode steps between), and the
    output is token-identical to the unbudgeted engine."""
    def build(budget):
        return Engine.from_preset(
            "debug-tiny", num_slots=2, slot_capacity=256,
            prefill_buckets=(16, 32, 64, 128), seed=0,
            kv_layout="paged", kv_page_size=16,
            prefill_chunk_budget=budget, prefix_cache=False,
        )

    async def run_long(eng):
        """(prefill steps spent on the long prompt, its text, whether the
        background decoder was still decoding when the long one finished —
        the chunk-count assertion only holds while a decoder is active, so
        callers must check it before trusting the step count)."""
        bg_out: list = []
        bg = asyncio.create_task(_consume(
            eng.stream(eng.tokenizer.encode("background decoder"),
                       SamplingParams(temperature=0.0, max_tokens=220)),
            bg_out,
        ))
        try:
            await _wait_for_text(bg_out, 2)
            before = eng.core.metrics.prefill_step.n
            long_ids = eng.tokenizer.encode("x" * 100)  # > 64, <= 128 bucket
            result = await eng.complete(
                long_ids, SamplingParams(temperature=0.0, max_tokens=8)
            )
            steps = eng.core.metrics.prefill_step.n - before
            bg_alive = not bg.done()
        finally:
            # ALWAYS reap the background stream — a timing-assert failure
            # that leaks it leaves an in-flight request decoding on the
            # engine, whose step-loop thread then outlives the test's
            # shutdown (stop()'s bounded join) and grinds every later
            # test's compiles on a small host
            bg.cancel()
            try:
                await bg
            except asyncio.CancelledError:
                pass
        return steps, result.text, bg_alive

    eng_budget = build(32)
    eng_free = build(0)
    try:
        async def run():
            # On a contended host the background decoder (220 tokens) can
            # drain before the long prompt's chunks finish, releasing the
            # budget mid-prefill; retry a couple of times and only assert
            # the chunk count when the decoder survived the whole window.
            for _ in range(3):
                steps_b, text_b, bg_alive = await run_long(eng_budget)
                if bg_alive:
                    break
            steps_f, text_f, _ = await run_long(eng_free)
            assert text_b == text_f
            assert steps_f == 1, f"expected one-shot prefill, got {steps_f}"
            if not bg_alive:
                pytest.skip("background decoder finished before the long "
                            "prompt on every attempt (contended host); "
                            "chunk-count assertion not meaningful")
            # 100 tokens at a 32-token budget: at least 4 chunked dispatches
            # vs exactly 1 one-shot dispatch unbudgeted
            assert steps_b >= 4, f"expected chunked prefill, got {steps_b}"
        asyncio.run(run())
    finally:
        eng_budget.shutdown()
        eng_free.shutdown()


# ------------------------------------------------- scheduler-level units


@pytest.fixture(scope="module")
def cold_core():
    """An EngineCore whose step loop is NEVER started: _try_insert and the
    class queues can be driven deterministically by hand."""
    core = EngineCore(get_preset("debug-tiny"), num_slots=2,
                      slot_capacity=64, prefill_buckets=(16,),
                      prefix_cache=False)
    yield core
    core._fail_all("test over")


def _req(prio=1, deadline_ms=None, tokens=(1, 2, 3)):
    return Request(
        prompt_ids=list(tokens),
        sampling=SamplingParams(temperature=0.0, max_tokens=4,
                                priority=prio, deadline_ms=deadline_ms),
    )


def test_class_queues_pop_strictly_by_priority(cold_core):
    reqs = [_req(2), _req(0), _req(1), _req(0)]
    for r in reqs:
        cold_core.pending.put(r)
    cold_core._drain_pending()
    depths = cold_core.queue_class_depths()
    assert depths == {"high": 2, "normal": 1, "low": 1}
    order = [cold_core._pop_request() for _ in range(4)]
    assert order == [reqs[1], reqs[3], reqs[2], reqs[0]]
    assert cold_core._pop_request() is None


def test_pop_prefers_more_important_class_over_held(cold_core):
    """A low-priority request wedged on the page pool (held) must not block
    a high-priority arrival — its page-pressure preemption is the very
    thing that can unwedge the pool (priority inversion regression)."""
    low, hi = _req(2), _req(0)
    cold_core._held_request = low
    cold_core._class_queues[0].append(hi)
    assert cold_core._head_priority() == 0
    assert cold_core._pop_request() is hi
    # the held request still owns the front of its own class
    assert cold_core._pop_request() is low
    assert cold_core._held_request is None
    assert cold_core._pop_request() is None


def test_hold_on_pool_never_overwrites_held(cold_core):
    a, b = _req(2), _req(0)
    cold_core._hold_on_pool(a)
    cold_core._hold_on_pool(b)  # second hold requeues, never drops `a`
    assert cold_core._held_request is a
    assert cold_core._pop_request() is b
    assert cold_core._pop_request() is a
    assert cold_core._pop_request() is None


def test_expired_deadline_is_shed_before_prefill(cold_core):
    req = _req(deadline_ms=1.0)
    time.sleep(0.01)
    cold_core.pending.put(req)
    shed_before = cold_core.metrics.deadline_shed_total
    assert cold_core._try_insert() is True  # handled work: the shed
    kind, value = req.events.get_nowait()
    assert kind == "error" and "deadline" in str(value)
    assert cold_core.metrics.deadline_shed_total == shed_before + 1
    # no slot was claimed, no dispatch ran
    assert all(s.request is None for s in cold_core.slots)


def test_sched_info_and_metrics_render(cold_core):
    info = cold_core.sched_info()
    assert set(info["queued_by_class"]) == {"high", "normal", "low"}
    text = cold_core.metrics.render(
        queue_depth=0, active_slots=0, num_slots=2,
        sched=cold_core.sched_info(),
    )
    assert "llmlb_engine_preemptions_total" in text
    assert "llmlb_engine_deadline_shed_total" in text
    assert 'llmlb_engine_queue_depth_class{priority="high"}' in text


def test_plan_wire_priority_and_deadline_survive():
    """Belt and braces on top of test_plan_wire's generic probe: the two
    new fields ride dataclasses.asdict -> SamplingParams(**payload)."""
    import dataclasses

    s = SamplingParams(priority=2, deadline_ms=1500.0)
    back = SamplingParams(**dataclasses.asdict(s))
    assert back.priority == 2 and back.deadline_ms == 1500.0


# --------------------------------------------------------- LoRA interaction


@pytest.fixture(scope="module")
def lora_engine(tmp_path_factory):
    """One decoding slot + an adapter store: a high-priority arrival MUST
    park the adapter-carrying victim, and the resume's chunk-prefill must
    re-read the SAME adapter deltas (docs/lora.md)."""
    from llmlb_tpu.lora import save_adapter

    d = tmp_path_factory.mktemp("adapters")
    cfg = get_preset("debug-tiny")
    save_adapter(str(d), "acme", cfg, rank=4)
    eng = Engine.from_preset(
        "debug-tiny", num_slots=1, slot_capacity=128,
        prefill_buckets=(16, 32), seed=0, kv_layout="paged",
        kv_page_size=16, lora_dir=str(d),
    )
    yield eng
    eng.shutdown()


def test_park_resume_with_active_adapter_greedy_identity(lora_engine):
    """Park/resume stays byte-identical with a LoRA attached: KV rebuilt by
    chunk-prefill runs through the adapter's wq/wk/wv deltas at identical
    absolute positions."""
    async def run():
        ref, got, preempted = await _preempt_roundtrip(
            lora_engine,
            SamplingParams(temperature=0.0, max_tokens=48, priority=2,
                           lora="acme"),
        )
        assert preempted >= 1, "high-priority arrival did not preempt"
        assert got == ref
        # sanity: the adapter actually changes the stream — identity would
        # be vacuous if the delta were dropped on both sides
        ids = lora_engine.tokenizer.encode("the quick brown fox jumps over")
        base = await lora_engine.complete(
            ids, SamplingParams(temperature=0.0, max_tokens=48)
        )
        assert base.text != ref
    asyncio.run(run())


def test_park_resume_with_active_adapter_seeded_identity(lora_engine):
    async def run():
        ref, got, preempted = await _preempt_roundtrip(
            lora_engine,
            SamplingParams(temperature=0.9, seed=4321, max_tokens=48,
                           priority=2, lora="acme"),
        )
        assert preempted >= 1
        assert got == ref
    asyncio.run(run())
