"""Prefix KV cache: radix-tree bookkeeping, scheduler reuse, perf smoke.

Three layers, mirroring the implementation split:
- PrefixCache unit tests (pure host-side: insert/match/refcount/evict,
  bucket alignment, LRU order, edge splitting).
- EngineCore integration (CPU backend): cache hits serve the shared head
  from copied KV rows, outputs stay greedy-identical to the cold path,
  cancellation mid-suffix-prefill releases the donor, disabled flag
  restores the old behavior.
- A fast perf smoke asserting a cache-hit insert dispatches NO prefill
  device step for the cached region — the tier-1 guard against silent
  re-prefill regressions.
"""

import queue

import numpy as np
import pytest

from llmlb_tpu.engine.prefix_cache import PrefixCache
from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams

# ----------------------------------------------------------------- radix tree


def make_cache(**kw):
    kw.setdefault("max_entries", 4)
    kw.setdefault("min_len", 4)
    kw.setdefault("align", 4)
    return PrefixCache(**kw)


def test_insert_and_exact_match():
    c = make_cache()
    assert c.insert((1, 2, 3, 4, 5, 6, 7, 8), slot=0) is not None
    got = c.match([1, 2, 3, 4, 5, 6, 7, 8, 9], max_len=8)
    assert got is not None
    entry, use_len = got
    assert entry.slot == 0
    assert use_len == 8
    assert c.pinned_slots() == {0}
    assert c.cached_tokens() == 8


def test_match_uses_partial_head_of_longer_entry():
    """KV rows for [0, m) depend only on tokens [0, m): a stored prefix can
    donate any of its own prefixes, including partway into a radix edge."""
    c = make_cache()
    c.insert(tuple(range(100, 112)), slot=1)  # 12 tokens
    # query shares only the first 6 tokens, then diverges
    got = c.match(list(range(100, 106)) + [999, 998], max_len=7)
    assert got is not None
    entry, use_len = got
    assert entry.slot == 1
    assert use_len == 4  # 6 matched, aligned down to the 4-token quantum


def test_match_respects_max_len_and_min_len():
    c = make_cache()
    c.insert((1, 2, 3, 4, 5, 6, 7, 8), slot=0)
    # an identical prompt must leave >= 1 suffix token: max_len = n - 1
    entry, use_len = c.match([1, 2, 3, 4, 5, 6, 7, 8], max_len=7)
    assert use_len == 4  # 7 aligned down
    # matches shorter than min_len are worthless
    assert c.match([1, 2, 3, 9], max_len=3) is None


def test_edge_split_on_divergent_insert():
    c = make_cache()
    c.insert((1, 2, 3, 4, 5, 6, 7, 8), slot=0)
    c.insert((1, 2, 3, 4, 9, 9, 9, 9), slot=1)  # splits the edge at depth 4
    e0, u0 = c.match([1, 2, 3, 4, 5, 6, 7, 8, 0], max_len=8)
    e1, u1 = c.match([1, 2, 3, 4, 9, 9, 9, 9, 0], max_len=8)
    assert (e0.slot, u0) == (0, 8)
    assert (e1.slot, u1) == (1, 8)
    assert len(c) == 2


def test_covers_blocks_duplicate_coverage_but_allows_extension():
    c = make_cache()
    c.insert((1, 2, 3, 4), slot=0)
    assert c.covers((1, 2, 3, 4))
    assert c.insert((1, 2, 3, 4), slot=1) is None  # no new coverage
    # a LONGER prefix is new coverage
    assert c.insert((1, 2, 3, 4, 5, 6, 7, 8), slot=1) is not None
    # ...and the short one is now covered by the long one too
    assert c.covers((1, 2, 3, 4))


def test_refcount_blocks_eviction():
    c = make_cache()
    e = c.insert((1, 2, 3, 4), slot=0)
    c.acquire(e)
    assert c.evict_lru() is None  # in-flight reader pins it
    c.release(e)
    assert c.evict_lru() == 0
    assert len(c) == 0
    assert c.match([1, 2, 3, 4, 5], max_len=4) is None


def test_lru_eviction_order_and_match_refreshes():
    c = make_cache()
    c.insert((1,) * 8, slot=0)
    c.insert((2,) * 8, slot=1)
    c.insert((3,) * 8, slot=2)
    c.match([1] * 9, max_len=8)  # a match refreshes slot 0's clock
    assert c.evict_lru() == 1    # slot 1 is now the oldest untouched
    assert c.evict_lru() == 2
    assert c.evict_lru() == 0
    assert c.evict_lru() is None


def test_evict_subsumed_reclaims_ancestor_donors():
    """A longer prefix covers every match its ancestors could serve; the
    ancestors' donor slots are reclaimed instead of bleeding the budget one
    slot per conversation turn."""
    c = make_cache()
    e1 = c.insert((1, 2, 3, 4), slot=0)
    turn2 = (1, 2, 3, 4, 5, 6, 7, 8)
    assert c.evict_subsumed(turn2) == [0]
    c.insert(turn2, slot=1)
    assert c.pinned_slots() == {1}
    # coverage is preserved: the short head still matches via the long entry
    entry, use_len = c.match([1, 2, 3, 4, 9], max_len=4)
    assert entry.slot == 1 and use_len == 4
    # an acquired ancestor is NOT reclaimed (in-flight reader)
    e2 = c.insert((9, 9, 9, 9), slot=2)
    c.acquire(e2)
    assert c.evict_subsumed((9, 9, 9, 9, 1, 1, 1, 1)) == []
    c.release(e2)
    assert e1.node is None  # removed entry is fully detached


def test_budget_rejects_insert_when_full():
    c = make_cache(max_entries=1)
    assert c.insert((1, 2, 3, 4), slot=0) is not None
    assert c.insert((5, 6, 7, 8), slot=1) is None  # caller must evict first
    assert c.evict_lru() == 0
    assert c.insert((5, 6, 7, 8), slot=1) is not None


def test_clear_drops_everything():
    c = make_cache()
    c.insert((1, 2, 3, 4), slot=0)
    c.insert((1, 2, 3, 4, 5, 6, 7, 8), slot=1)
    c.clear()
    assert len(c) == 0
    assert c.match([1, 2, 3, 4, 5], max_len=4) is None


# ---------------------------------------------------------------- engine core


def _run(core, prompt_ids, *, max_tokens=4, temperature=0.0):
    r = Request(prompt_ids=list(prompt_ids),
                sampling=SamplingParams(temperature=temperature,
                                        max_tokens=max_tokens))
    core.submit(r)
    toks = []
    while True:
        kind, value = r.events.get(timeout=120)
        if kind == "token":
            toks.append(value)
        elif kind == "error":
            raise AssertionError(f"engine error: {value}")
        else:
            return toks, value


@pytest.fixture(scope="module")
def prompt():
    rng = np.random.default_rng(7)
    cfg = get_preset("debug-tiny")
    return list(rng.integers(1, cfg.vocab_size, size=(48,)))


# Every engine-core test runs over BOTH KV layouts: dense (pinned donor
# slots + device-side row copies) and paged (zero-copy page sharing). The
# 16-token page size matches the prefill bucket so aligned lengths — and
# every counter assertion below — are identical across layouts.
@pytest.fixture(params=["dense", "paged"])
def kv_layout(request):
    return request.param


def make_core(kv_layout, **kw):
    kw.setdefault("kv_page_size", 16)
    return EngineCore(get_preset("debug-tiny"), kv_layout=kv_layout, **kw)


def test_cache_hit_reuses_prefix_and_matches_cold_output(prompt, kv_layout):
    """Warm identical prompt: hit counters move, cached tokens are the
    aligned head, and greedy output equals the cold run's (the copied KV
    rows are the same numbers the cold prefill computed)."""
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), seed=0)
    core.start()
    try:
        cold_toks, cold_fin = _run(core, prompt)
        m = core.metrics
        assert m.prefix_misses_total == 1
        assert m.prefix_insertions_total == 1
        info = core.prefix_cache_info()
        assert info["enabled"] and info["entries"] == 1
        assert info["cached_tokens"] == 48

        warm_toks, warm_fin = _run(core, prompt)
        assert m.prefix_hits_total == 1
        # 48-token prompt: reusable head is min(47, ...) aligned to 16 -> 32
        assert m.prefix_cached_tokens_total == 32
        assert (warm_toks, warm_fin) == (cold_toks, cold_fin)
    finally:
        core.stop()


def test_divergent_tail_still_hits_shared_head(prompt, kv_layout):
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), seed=0)
    core.start()
    try:
        _run(core, prompt)
        # tail diverges at position 40 (p-1 stays in vocab: prompt ids >= 1)
        other = prompt[:40] + [p - 1 for p in prompt[40:]]
        _run(core, other)
        assert core.metrics.prefix_hits_total == 1
        assert core.metrics.prefix_cached_tokens_total == 32  # 40 aligned
    finally:
        core.stop()


def test_slot_pressure_evicts_donors_for_live_traffic(kv_layout):
    """With every non-pinned slot busy and requests queued, pinned donors
    are evicted LRU rather than starving the queue (dense); in paged mode
    the same budget bound churns ENTRIES instead of slots."""
    cfg = get_preset("debug-tiny")
    rng = np.random.default_rng(3)
    core = make_core(kv_layout, num_slots=2, slot_capacity=64,
                     prefill_buckets=(16,), prefix_cache_slots=1, seed=0)
    core.start()
    try:
        prompts = [list(rng.integers(1, cfg.vocab_size, size=(20,)))
                   for _ in range(4)]
        for p in prompts:
            _run(core, p)  # each completion pins (budget 1 -> evictions)
        assert core.metrics.prefix_evictions_total >= 1
        assert core.stats().active_slots == 0
        assert len(core.prefix_cache) <= 1
    finally:
        core.stop()


def _drive_to_completion(core, request, limit=500):
    """Run the step loop inline (core not started) until `request` finishes —
    the same call sequence _loop makes, but deterministic for tests."""
    core.pending.put(request)
    for _ in range(limit):
        core._try_insert()
        core._advance_prefill()
        core._decode_active()
        try:
            while True:
                kind, value = request.events.get_nowait()
                if kind in ("done", "error"):
                    return kind, value
        except queue.Empty:
            pass
    raise AssertionError("request did not finish")


def test_cancel_mid_suffix_prefill_releases_entry(prompt, kv_layout):
    """A cache-hit request cancelled during its suffix prefill must release
    the donor entry (refcount back to 0) so it stays evictable. Driven
    inline — the loop thread is never started — so the cancellation lands
    exactly between the KV-row copy and the first suffix chunk."""
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), seed=0)
    # warm the cache with one completed request
    kind, _ = _drive_to_completion(
        core, Request(prompt_ids=list(prompt),
                      sampling=SamplingParams(temperature=0.0, max_tokens=2)))
    assert kind == "done"
    (entry,) = core.prefix_cache.entries()

    r = Request(prompt_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=8))
    core.pending.put(r)
    core._try_insert()  # hit: copies rows, acquires the donor, prefilling
    assert core.metrics.prefix_hits_total == 1
    assert entry.refcount == 1
    assert core.prefix_cache.evict_lru() is None  # reader pins the donor

    r.cancel()
    core._advance_prefill()  # observes the cancellation mid-suffix-prefill
    assert r.events.get_nowait() == ("done", "cancelled")
    assert entry.refcount == 0
    assert core.prefix_cache.evict_lru() is not None  # evictable again


def test_multi_turn_conversation_reuses_one_donor_slot(prompt, kv_layout):
    """Growing-conversation shape: each turn extends the last prompt. The
    cache must hold ONE entry for the conversation (ancestors reclaimed),
    not one pinned slot (or page set) per turn."""
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), prefix_cache_slots=3, seed=0)
    core.start()
    try:
        turn = list(prompt[:16])
        for growth in (16, 16):  # 16 -> 32 -> 48 tokens
            _run(core, turn)
            turn = turn + [p - 1 for p in prompt[:growth]]
        _run(core, turn)
        assert len(core.prefix_cache) == 1  # one donor covers all turns
        (entry,) = core.prefix_cache.entries()
        assert entry.length == 48
    finally:
        core.stop()


def test_env_var_disables_prefix_cache(monkeypatch):
    """LLMLB_PREFIX_CACHE accepts the same off vocabulary as the CLI flag —
    an operator's emergency disable must not silently no-op."""
    for value in ("0", "false", "off", "no"):
        monkeypatch.setenv("LLMLB_PREFIX_CACHE", value)
        core = EngineCore(get_preset("debug-tiny"), num_slots=2,
                          slot_capacity=64, prefill_buckets=(16,), seed=0)
        assert core.prefix_cache is None, value
    monkeypatch.setenv("LLMLB_PREFIX_CACHE", "1")
    core = EngineCore(get_preset("debug-tiny"), num_slots=2,
                      slot_capacity=64, prefill_buckets=(16,), seed=0)
    assert core.prefix_cache is not None


def test_disabled_flag_restores_plain_scheduler(prompt, kv_layout):
    core = make_core(kv_layout, num_slots=2, slot_capacity=64,
                     prefill_buckets=(16,), prefix_cache=False, seed=0)
    core.start()
    try:
        assert core.prefix_cache is None
        assert core.prefix_cache_info() == {"enabled": False}
        _run(core, prompt)
        _run(core, prompt)
        m = core.metrics
        assert (m.prefix_hits_total, m.prefix_misses_total,
                m.prefix_insertions_total) == (0, 0, 0)
    finally:
        core.stop()


def test_prefix_metrics_in_prometheus_and_summary(prompt, kv_layout):
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), seed=0)
    core.start()
    try:
        _run(core, prompt)
        _run(core, prompt)
        stats = core.stats()
        text = core.metrics.render(
            queue_depth=stats.queued, active_slots=stats.active_slots,
            num_slots=stats.num_slots, prefix_cache=core.prefix_cache_info(),
        )
        assert "llmlb_engine_prefix_cache_hits_total 1" in text
        assert "llmlb_engine_prefix_cache_misses_total 1" in text
        assert "llmlb_engine_prefix_cache_cached_tokens_total 32" in text
        assert "llmlb_engine_prefix_cache_evictions_total 0" in text
        if kv_layout == "paged":
            # zero-copy donors pin pages, never slots
            assert "llmlb_engine_prefix_cache_pinned_slots 0" in text
            assert "llmlb_engine_prefix_cache_pinned_pages 3" in text
        else:
            assert "llmlb_engine_prefix_cache_pinned_slots 1" in text
        assert "llmlb_engine_prefix_cache_pinned_hbm_bytes" in text
        summary = core.metrics.summary()
        assert summary["prefix_hits_total"] == 1
        assert summary["prefix_cached_tokens_total"] == 32
    finally:
        core.stop()


# ----------------------------------------------------------------- perf smoke


def test_cache_hit_skips_prefill_for_cached_region(prompt, kv_layout):
    """Tier-1 regression guard: a hit must dispatch prefill steps ONLY for
    the uncached suffix. 48-token prompt over 16-token chunks: 3 dispatches
    cold, exactly 1 warm (32 tokens ride the device-side row copy in dense
    mode, the donor's shared pages in paged mode — which must additionally
    dispatch ZERO cache copies)."""
    core = make_core(kv_layout, num_slots=4, slot_capacity=64,
                     prefill_buckets=(16,), seed=0)
    core.start()
    try:
        m = core.metrics
        _run(core, prompt)
        cold_steps = m.prefill_step.n
        assert cold_steps == 3
        _run(core, prompt)
        warm_steps = m.prefill_step.n - cold_steps
        assert m.prefix_hits_total == 1
        assert warm_steps == 1, (
            f"cache hit re-prefilled the cached region: {warm_steps} "
            f"dispatches for a 16-token suffix"
        )
        if kv_layout == "paged":
            assert core.kv_copy_dispatches == 0
    finally:
        core.stop()


def test_engine_health_and_system_carry_prefix_block():
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    async def run():
        engine = Engine.from_preset(
            "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
        )
        client = TestClient(TestServer(create_engine_app(engine)))
        await client.start_server()
        try:
            health = await (await client.get("/api/health")).json()
            assert health["prefix_cache"]["enabled"] is True
            assert health["prefix_cache"]["budget_slots"] == 1
            assert "prefix_hits_total" in health["metrics"]
            system = await (await client.get("/api/system")).json()
            assert system["prefix_cache"]["enabled"] is True
            metrics_text = await (await client.get("/metrics")).text()
            assert "llmlb_engine_prefix_cache_hits_total" in metrics_text
        finally:
            await client.close()
            engine.core.stop()

    asyncio.run(run())
