"""Int8 quantized serving (llmlb_tpu/quant, docs/quantization.md).

Covers the acceptance bars at the engine level on the CPU backend:
- `quantize="off"` is provably zero-cost: greedy AND seeded streams are
  bit-identical to an engine constructed without the knob, both layouts.
- int8-KV engines serve end to end (prefill, decode, chunked prefill,
  prefix-cache zero-copy sharing) and report halved bytes/page.
- spec-decode on int8 pages: rejected-suffix rollback releases pages
  exactly once (PagePool double-free guard armed) and the pool drains
  clean at a tiny page size.
- weight quantization: params carry int8+scale pairs, output stays
  plausible (greedy decode completes), and the streaming checkpoint
  loader produces the same layout the core's own pass does.
"""

import queue

import numpy as np
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, Request, SamplingParams


def _req(prompt, max_tokens=8, temperature=0.0, seed=None, spec=None):
    return Request(prompt_ids=list(prompt),
                   sampling=SamplingParams(temperature=temperature,
                                           max_tokens=max_tokens,
                                           seed=seed, speculative=spec))


def _collect(request, timeout=120):
    toks = []
    while True:
        kind, value = request.events.get(timeout=timeout)
        if kind == "token":
            toks.append(value)
        elif kind == "error":
            raise AssertionError(f"engine error: {value}")
        else:
            return toks, value


def _core(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("slot_capacity", 64)
    kw.setdefault("prefill_buckets", (16, 32))
    kw.setdefault("seed", 0)
    kw.setdefault("kv_page_size", 16)
    return EngineCore(get_preset("debug-tiny"), **kw)


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    cfg = get_preset("debug-tiny")
    return [list(rng.integers(1, cfg.vocab_size, size=(n,)))
            for n in (24, 12, 40)]


# ------------------------------------------------------- off == bit-identical


@pytest.mark.parametrize("kv_layout", ["paged", "dense"])
def test_quantize_off_bit_identical(prompts, kv_layout):
    """The zero-cost-when-disabled acceptance bar: greedy and seeded
    stochastic streams from a quantize="off" engine match an engine built
    without the knob token for token."""
    streams = {}
    for label, quantize in (("default", None), ("off", "off")):
        core = _core(kv_layout=kv_layout, quantize=quantize)
        core.start()
        try:
            reqs = [
                _req(prompts[0], max_tokens=10),  # greedy
                _req(prompts[1], max_tokens=10, temperature=0.9, seed=42),
                _req(prompts[2], max_tokens=10, temperature=0.7, seed=7),
            ]
            for r in reqs:
                core.submit(r)
            streams[label] = [_collect(r)[0] for r in reqs]
        finally:
            core.stop()
    assert streams["default"] == streams["off"]


# -------------------------------------------------------------- int8 KV pages


def test_int8_kv_serves_and_reports_halved_bytes(prompts):
    # prefix_cache off so the drain check below sees a fully-free pool
    # (donor pins are covered by test_int8_kv_prefix_hit_stays_zero_copy)
    core = _core(quantize="kv", prefix_cache=False)
    core.start()
    try:
        reqs = [_req(p, max_tokens=6) for p in prompts]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            toks, finish = _collect(r)
            assert finish in ("stop", "length")
            assert len(toks) >= 1
        info = core.kv_cache_info()
        assert info["kv_dtype"] == "int8"
        bf16 = _core(quantize="off")
        try:
            base = bf16.kv_cache_info()
        finally:
            bf16.stop()
        # (D·1 + 4) / (D·itemsize): strictly under 60% of the bf16 page
        assert info["bytes_per_page"] < 0.6 * base["bytes_per_page"]
        assert info["hbm_bytes"] < 0.6 * base["hbm_bytes"]
        # pool fully reclaimed (scales carry no separate page bookkeeping)
        assert core.page_pool.available() == core.page_pool.total
    finally:
        core.stop()


def test_int8_kv_prefix_hit_stays_zero_copy(prompts):
    """Zero-copy sharing is page-id bookkeeping; the scale arrays ride the
    same ids, so a hit must still dispatch no device copy."""
    core = _core(quantize="kv")
    core.start()
    try:
        _collect(core.submit(_req(prompts[2])))
        _collect(core.submit(_req(prompts[2])))
        assert core.metrics.prefix_hits_total == 1
        assert core.kv_copy_dispatches == 0
    finally:
        core.stop()


def test_int8_kv_greedy_parity_with_bf16(prompts):
    """Token-level divergence is allowed but must be mild on a tiny model
    with short generations: the first few greedy tokens track bf16."""
    outs = {}
    for label, quantize in (("bf16", "off"), ("int8", "kv")):
        core = _core(quantize=quantize)
        core.start()
        try:
            r = _req(prompts[0], max_tokens=6)
            core.submit(r)
            outs[label] = _collect(r)[0]
        finally:
            core.stop()
    assert len(outs["int8"]) == len(outs["bf16"])
    # prefix attention reads fresh bf16 K/V, so the FIRST token (sampled
    # from prefill logits) is exact; later tokens may drift
    assert outs["int8"][0] == outs["bf16"][0]


def test_spec_decode_on_int8_pages_rolls_back_cleanly():
    """Speculative decoding over int8 pages: rejected-suffix rollback
    releases over-allocated pages exactly once (the PagePool double-free
    guard would raise otherwise) and the pool drains clean at page_size 4.
    Prompts with repeated n-grams guarantee the drafter proposes."""
    cfg = get_preset("debug-tiny")
    core = EngineCore(cfg, num_slots=4, slot_capacity=64,
                      prefill_buckets=(16, 32), seed=0, kv_layout="paged",
                      kv_page_size=4, quantize="kv", spec_decode=True,
                      spec_max_draft=3, prefix_cache=False)
    core.start()
    try:
        pattern = [9, 8, 7, 6] * 6  # strong n-gram structure
        reqs = [_req(pattern, max_tokens=16,
                     spec={"enabled": True, "max_draft_tokens": 3})
                for _ in range(4)]
        for r in reqs:
            core.submit(r)
        for r in reqs:
            toks, finish = _collect(r)
            assert finish in ("stop", "length")
            assert len(toks) >= 1
        assert core.metrics.spec_verify_steps_total >= 1
        assert core.page_pool.available() == core.page_pool.total, (
            "int8 spec-decode rollback leaked or double-freed pages"
        )
    finally:
        core.stop()


def test_int8_kv_seeded_stream_is_reproducible(prompts):
    """Per-request seeds stay deterministic on quantized pages (two runs,
    same engine config, identical streams)."""
    runs = []
    for _ in range(2):
        core = _core(quantize="kv")
        core.start()
        try:
            r = _req(prompts[1], max_tokens=8, temperature=0.8, seed=11)
            core.submit(r)
            runs.append(_collect(r)[0])
        finally:
            core.stop()
    assert runs[0] == runs[1]


# ------------------------------------------------------------- int8 weights


def test_int8_weights_layout_and_serving(prompts):
    core = _core(quantize="weights")
    core.start()
    try:
        assert core.params["wq"].dtype == np.int8
        assert "wq_scale" in core.params
        assert core.quant_info()["param_bytes"] < core.quant_info()[
            "param_bytes_bf16"
        ]
        r = _req(prompts[0], max_tokens=6)
        core.submit(r)
        toks, finish = _collect(r)
        assert finish in ("stop", "length") and len(toks) >= 1
    finally:
        core.stop()


def test_quantize_all_through_service_health():
    from llmlb_tpu.engine.service import Engine

    eng = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,),
        kv_page_size=16, quantize="all",
    )
    try:
        health = eng.health()
        assert health["quant"]["mode"] == "all"
        assert health["kv_cache"]["kv_dtype"] == "int8"
        stats = eng.core.stats()
        text = eng.core.metrics.render(
            queue_depth=stats.queued, active_slots=stats.active_slots,
            num_slots=stats.num_slots, kv_cache=eng.core.kv_cache_info(),
            quant=eng.core.quant_info(),
        )
        assert 'llmlb_engine_quant_mode{mode="all"} 1' in text
        assert "llmlb_engine_kv_bytes_per_page" in text
        assert "llmlb_engine_param_bytes" in text
    finally:
        eng.shutdown()


def test_dense_layout_rejects_kv_quant_gracefully():
    core = _core(kv_layout="dense", quantize="all")
    try:
        assert core.quant.weights and not core.quant.kv
        assert core.kv_cache_info()["kv_dtype"] != "int8"
    finally:
        core.stop()


def test_streaming_loader_matches_core_quantization(tmp_path):
    """engine/weights.py quantize-while-streaming must produce the same
    int8 layout EngineCore's own pass produces from the same checkpoint."""
    import jax
    from safetensors.numpy import save_file

    from llmlb_tpu.engine.weights import load_checkpoint
    from llmlb_tpu.models import llama
    from llmlb_tpu.quant import quantize_params

    cfg = get_preset("debug-tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    state = {}
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}"
        state[f"{pre}.self_attn.q_proj.weight"] = np.asarray(
            params["wq"][i]).T
        state[f"{pre}.self_attn.k_proj.weight"] = np.asarray(
            params["wk"][i]).T
        state[f"{pre}.self_attn.v_proj.weight"] = np.asarray(
            params["wv"][i]).T
        state[f"{pre}.self_attn.o_proj.weight"] = np.asarray(
            params["wo"][i]).T
        state[f"{pre}.mlp.gate_proj.weight"] = np.asarray(params["wg"][i]).T
        state[f"{pre}.mlp.up_proj.weight"] = np.asarray(params["wu"][i]).T
        state[f"{pre}.mlp.down_proj.weight"] = np.asarray(params["wd"][i]).T
        state[f"{pre}.input_layernorm.weight"] = np.asarray(
            params["ln_attn"][i])
        state[f"{pre}.post_attention_layernorm.weight"] = np.asarray(
            params["ln_mlp"][i])
    state["model.embed_tokens.weight"] = np.asarray(params["embed"])
    state["model.norm.weight"] = np.asarray(params["ln_final"])
    state["lm_head.weight"] = np.asarray(params["lm_head"]).T
    save_file(state, str(tmp_path / "model.safetensors"))

    loaded = load_checkpoint(str(tmp_path), cfg, quantize_weights=True)
    direct = quantize_params({k: np.asarray(v) for k, v in params.items()})
    assert set(loaded) == set(direct)
    for name in ("wq", "wq_scale", "wd", "wd_scale"):
        np.testing.assert_array_equal(np.asarray(loaded[name]),
                                      np.asarray(direct[name]))
