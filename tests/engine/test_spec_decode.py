"""Speculative decoding through the real scheduler on CPU JAX.

The load-bearing guarantees:
- greedy parity: speculation changes WHEN tokens are computed, never WHICH —
  spec-on output is token-exact vs the non-speculative engine, including a
  mixed batch where only some slots speculate;
- seeded parity: per-request seeded sampling folds the PRNG key by global
  position, so seeded streams are bit-identical with speculation on or off;
- constrained bursts: a JSON-mode request rides the verify path multi-token
  (no batch-wide single-step penalty) and stays 100% schema-valid with
  masked-step accounting intact;
- KV-page rollback: rejected drafts release over-allocated pages exactly
  once (the PagePool double-free guard stays armed), including the
  page-boundary case where the rollback empties the slot's last page.
"""

import asyncio
import json

import jsonschema
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.engine.scheduler import EngineCore, SamplingParams
from llmlb_tpu.engine.service import Engine

PROMPT = "count: 1 2 3 4 5 6 7 8 9 then repeat: 1 2 3 4 5"

SCHEMA = {
    "type": "object",
    "properties": {
        "ok": {"type": "boolean"},
        "tag": {"enum": ["alpha", "beta"]},
    },
    "required": ["ok", "tag"],
}

# An array of identical items: the grammar plus greedy decode makes the
# continuation maximally predictable, so prompt-lookup drafts accept at a
# high rate — the shape speculation exists to accelerate.
ARRAY_SCHEMA = {
    "type": "array",
    "items": {"enum": ["aa"]},
    "minItems": 6,
    "maxItems": 6,
}


def _engine(spec: bool, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("slot_capacity", 256)
    kw.setdefault("prefill_buckets", (16, 32, 64))
    return Engine.from_preset("debug-tiny", spec_decode=spec, **kw)


def _ids(eng, text=PROMPT):
    return eng.encode_chat([{"role": "user", "content": text}])


@pytest.mark.parametrize("kv_layout", ["paged", "dense"])
def test_greedy_parity_token_exact(kv_layout):
    async def collect(spec):
        eng = _engine(spec, kv_layout=kv_layout)
        try:
            r = await eng.complete(
                _ids(eng), SamplingParams(temperature=0.0, max_tokens=120)
            )
            steps = eng.core.metrics.spec_verify_steps_total
            return r.text, r.finish_reason, steps
        finally:
            eng.shutdown()

    base_text, base_fin, base_steps = asyncio.run(collect(False))
    spec_text, spec_fin, spec_steps = asyncio.run(collect(True))
    assert base_steps == 0  # spec off: the verify path never dispatches
    assert spec_steps > 0  # spec on: it actually ran, this is not a no-op
    assert (spec_text, spec_fin) == (base_text, base_fin)


def test_greedy_parity_mixed_batch_some_slots_speculate():
    """Per-request opt-out: slots with speculation disabled share the batch
    with speculating slots and still produce the exact baseline tokens."""
    prompts = [PROMPT, "alpha beta alpha beta alpha", "once upon a time",
               "aa bb aa bb aa bb"]

    async def collect(engine_spec, per_request):
        eng = _engine(engine_spec)
        try:
            outs = await asyncio.gather(*(
                eng.complete(
                    _ids(eng, p),
                    SamplingParams(temperature=0.0, max_tokens=48,
                                   speculative=knob),
                )
                for p, knob in zip(prompts, per_request)
            ))
            return [r.text for r in outs], eng.core.metrics
        finally:
            eng.shutdown()

    baseline, _ = asyncio.run(collect(False, [None] * 4))
    mixed_knobs = [{"enabled": True}, {"enabled": False},
                   {"enabled": True, "max_draft_tokens": 2}, None]
    mixed, metrics = asyncio.run(collect(True, mixed_knobs))
    assert mixed == baseline
    assert metrics.spec_verify_steps_total > 0


def test_seeded_sampling_bit_identical_with_speculation():
    """temperature>0 with a seed: the per-position key fold makes the token
    stream a pure function of (seed, position), so speculation cannot
    change it — the strongest distribution-preservation check available."""
    async def collect(spec):
        eng = _engine(spec)
        try:
            r = await eng.complete(
                _ids(eng),
                SamplingParams(temperature=1.0, max_tokens=96, seed=1234),
            )
            return r.text, r.finish_reason
        finally:
            eng.shutdown()

    assert asyncio.run(collect(True)) == asyncio.run(collect(False))


def test_constrained_json_decodes_multi_token_via_speculation():
    """A JSON-mode request must ride the verify path (multi-token steps with
    per-position masks) instead of forcing batch-wide single-step decode:
    drafts are accepted, output stays schema-valid, and masked-step
    accounting still fires."""
    async def run():
        eng = _engine(True)
        try:
            constrained = [
                eng.complete(
                    _ids(eng, f"emit array {i}"),
                    SamplingParams(temperature=0.0, max_tokens=64,
                                   constraint={"type": "json_schema",
                                               "schema": ARRAY_SCHEMA}),
                )
                for i in range(2)
            ]
            free = [
                eng.complete(_ids(eng, f"free {i}"),
                             SamplingParams(temperature=0.0, max_tokens=24))
                for i in range(2)
            ]
            results = await asyncio.gather(*constrained, *free)
            return results, eng.core.metrics, eng.core.spec_info()
        finally:
            eng.shutdown()

    results, metrics, info = asyncio.run(run())
    for r in results[:2]:
        assert r.finish_reason == "stop"
        jsonschema.validate(json.loads(r.text), ARRAY_SCHEMA)
    assert metrics.constraint_violations_total == 0
    # the verify path ran with grammar masks applied (each masked verify
    # dispatch counts exactly like a masked single-step decode)
    assert metrics.masked_decode_steps_total > 0
    assert metrics.spec_verify_steps_total > 0
    # multi-token for constrained output: accepted drafts mean at least one
    # step emitted >= 2 tokens for a speculating (constrained) slot
    assert metrics.spec_accepted_tokens_total > 0
    assert info["acceptance_rate"] > 0


def test_constrained_schema_valid_mixed_with_object_schema():
    """Object-schema JSON under speculation: output identical to the
    non-speculative constrained baseline under greedy decode."""
    async def collect(spec):
        eng = _engine(spec)
        try:
            r = await eng.complete(
                _ids(eng, "produce json"),
                SamplingParams(temperature=0.0, max_tokens=64,
                               constraint={"type": "json_schema",
                                           "schema": SCHEMA}),
            )
            return r.text, r.finish_reason
        finally:
            eng.shutdown()

    base = asyncio.run(collect(False))
    spec = asyncio.run(collect(True))
    assert spec == base
    jsonschema.validate(json.loads(spec[0]), SCHEMA)


def test_verify_steps_have_their_own_kind_and_phase_records():
    """stepstats: verify dispatches record kind='verify' with a draft phase,
    keep their own EMA baseline, and the spec series reach /metrics."""
    async def run():
        eng = _engine(True)
        try:
            await eng.complete(
                _ids(eng), SamplingParams(temperature=0.0, max_tokens=120)
            )
            snap = eng.core.step_stats.snapshot(limit=256)
            stats = eng.core.stats()
            text = eng.core.metrics.render(
                queue_depth=stats.queued, active_slots=stats.active_slots,
                num_slots=stats.num_slots,
            )
            return snap, text
        finally:
            eng.shutdown()

    snap, exposition = asyncio.run(run())
    kinds = {r["kind"] for r in snap["records"]}
    assert "verify" in kinds
    assert "verify" in snap["ema_step_s"]
    verify = [r for r in snap["records"] if r["kind"] == "verify"]
    assert all("draft" in r["phases_s"] for r in verify)
    # emitted tokens ride the record (decode-tokens accounting for MFU)
    assert any(r["tokens"] >= 1 for r in verify)
    for series in ("llmlb_engine_spec_verify_steps_total",
                   "llmlb_engine_spec_draft_tokens_total",
                   "llmlb_engine_spec_accepted_tokens_total",
                   "llmlb_engine_spec_emitted_tokens_total"):
        assert series in exposition


def test_spec_info_surfaces_in_health():
    eng = _engine(True)
    try:
        health = eng.health()
        assert health["spec"]["enabled"] is True
        assert health["spec"]["available"] is True
        assert health["spec"]["max_draft_tokens"] >= 1
    finally:
        eng.shutdown()


# --------------------------------------------------------- page rollback edges


def _paged_core(**kw):
    cfg = get_preset("debug-tiny")
    kw.setdefault("num_slots", 2)
    kw.setdefault("slot_capacity", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_page_size", 4)
    kw.setdefault("prefix_cache", False)
    return EngineCore(cfg, **kw)


def test_trim_releases_over_allocated_pages_exactly_once():
    core = _paged_core()
    pool = core.page_pool
    free0 = pool.available()
    pages = core._try_reserve_pages(4)  # covers 16 tokens at page_size 4
    core._assign_slot_pages(0, (), pages)
    core._seq_lens[0] = 9  # committed 9 tokens; next write at 9
    # keep pages covering committed+1 = 10 tokens -> 3 pages, release 1
    core._trim_slot_pages(0, 10)
    assert pool.available() == free0 - 3
    assert len(core._slot_pages[0]) == 3
    assert core._block_tables[0, 3] == 0
    # trimming again is a no-op, NOT a double free
    core._trim_slot_pages(0, 10)
    assert pool.available() == free0 - 3
    # freeing the slot releases the remaining pages exactly once
    core._free_slot_kv(0)
    assert pool.available() == free0


def test_trim_page_boundary_rollback_empties_last_page():
    """Rollback landing exactly on a page boundary: the last page holds only
    rejected-draft garbage and must be released in full."""
    core = _paged_core()
    pool = core.page_pool
    free0 = pool.available()
    pages = core._try_reserve_pages(3)  # 12 tokens of room
    core._assign_slot_pages(0, (), pages)
    core._seq_lens[0] = 7  # committed 7; keep = pages_for(8) = 2 pages
    core._trim_slot_pages(0, 8)
    assert len(core._slot_pages[0]) == 2
    assert pool.available() == free0 - 2
    core._free_slot_kv(0)
    assert pool.available() == free0


def test_spec_traffic_leaves_page_pool_clean():
    """End to end on a tiny page size: rejected drafts across many verify
    steps must leave zero leaked or double-freed pages once traffic drains
    (the engine would raise PageError mid-loop on any double free)."""
    async def run():
        eng = Engine.from_preset(
            "debug-tiny", spec_decode=True, num_slots=4, slot_capacity=128,
            prefill_buckets=(16, 32), kv_layout="paged", kv_page_size=4,
            prefix_cache=False,
        )
        try:
            outs = await asyncio.gather(*(
                eng.complete(_ids(eng, f"{PROMPT} v{i}"),
                             SamplingParams(temperature=0.0, max_tokens=40))
                for i in range(6)
            ))
            assert all(r.finish_reason in ("stop", "length") for r in outs)
            assert eng.core.metrics.spec_verify_steps_total > 0
            # drained: every page back in the pool
            return eng.core.page_pool.used()
        finally:
            eng.shutdown()

    assert asyncio.run(run()) == 0


async def test_engine_http_speculative_knob_and_validation():
    """The OpenAI-dialect `speculative` body knob reaches the scheduler
    (spec engages on an engine defaulting OFF) and malformed knobs 400
    with the offending field named."""
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app

    eng = _engine(False)  # engine default off; the request opts in
    client = TestClient(TestServer(create_engine_app(eng, owns_engine=False)))
    await client.start_server()
    try:
        payload = {
            "model": eng.model_id,
            "messages": [{"role": "user", "content": PROMPT}],
            "max_tokens": 100, "temperature": 0.0,
            "speculative": {"enabled": True, "max_draft_tokens": 4},
        }
        resp = await client.post("/v1/chat/completions", json=payload)
        assert resp.status == 200, await resp.text()
        await resp.json()
        assert eng.core.metrics.spec_verify_steps_total > 0

        for bad in ("yes", {"enabled": "yes"}, {"max_draft_tokens": 0},
                    {"max_draft_tokens": True}):
            resp = await client.post("/v1/chat/completions", json={
                **payload, "speculative": bad,
            })
            assert resp.status == 400, bad
            err = await resp.json()
            assert "speculative" in err["error"]["message"]
    finally:
        await client.close()
        eng.shutdown()
