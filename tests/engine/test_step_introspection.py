"""Step-phase introspection, live MFU accounting, profiler capture, and the
TPU init-probe plumbing (PR 6 tentpole surfaces).

Covers: StepRecorder ring/anomaly/window semantics, the chip-spec and
FLOPs/bytes-per-token helpers, the CPU engine end to end (/api/steps,
phase histograms in /metrics, perf block in /api/system), the < 1%
instrumentation-overhead guarantee, and POST /api/profile producing a
non-empty downloadable trace on CPU JAX.
"""

import io
import time
import zipfile

import pytest

from llmlb_tpu.engine.stepstats import PHASES, StepRecorder
from llmlb_tpu.engine.telemetry import (
    chip_spec_for,
    model_bytes_per_token,
    model_flops_per_token,
)

# ------------------------------------------------------------- recorder units


def test_step_recorder_ring_wraparound():
    rec = StepRecorder(capacity=4)
    for i in range(10):
        rec.observe("decode", {"compute": 0.001}, tokens=1)
    snap = rec.snapshot(limit=10)
    assert snap["steps_total"] == 10
    assert snap["buffered"] == 4
    assert [r["seq"] for r in snap["records"]] == [10, 9, 8, 7]
    # limit caps below capacity too, newest first
    assert [r["seq"] for r in rec.snapshot(limit=2)["records"]] == [10, 9]


def test_step_recorder_flags_slow_steps_after_warmup():
    rec = StepRecorder(slow_floor_s=0.0)
    # warmup + baseline: 30 steps of ~1ms
    for _ in range(30):
        assert rec.observe("decode", {"compute": 0.001}) is False
    ema_before = rec.snapshot()["ema_step_s"]["decode"]
    # a 40x step flags...
    assert rec.observe("decode", {"compute": 0.040}) is True
    assert rec.slow_steps_total == 1
    # ...and must NOT drag the baseline up (else it masks the next one)
    assert rec.snapshot()["ema_step_s"]["decode"] == pytest.approx(
        ema_before
    )
    assert rec.observe("decode", {"compute": 0.040}) is True
    snap = rec.snapshot(slow_only=True)
    assert len(snap["records"]) == 2
    assert all(r["slow"] for r in snap["records"])
    # prefill has its own baseline: a first prefill step never flags
    assert rec.observe("prefill", {"compute": 0.5}) is False


def test_step_recorder_warmup_never_flags():
    rec = StepRecorder(slow_floor_s=0.0)
    flagged = [rec.observe("decode", {"compute": 0.001 * (i + 1)})
               for i in range(10)]
    assert not any(flagged)


def test_step_recorder_window_throughput_decode_only():
    rec = StepRecorder(window=4)
    rec.observe("prefill", {"compute": 1.0}, tokens=100)  # excluded
    for _ in range(6):  # window keeps the last 4
        rec.observe("decode", {"compute": 0.01, "fetch": 0.01}, tokens=8)
    busy, toks = rec.window_throughput()
    assert toks == 32
    assert busy == pytest.approx(4 * 0.02)
    assert StepRecorder().window_throughput() == (0.0, 0)


def test_step_recorder_snapshot_copies_records():
    rec = StepRecorder()
    rec.observe("decode", {"compute": 0.00123456789}, tokens=1)
    a = rec.snapshot()["records"][0]
    a["phases_s"]["compute"] = 999.0
    b = rec.snapshot()["records"][0]
    assert b["phases_s"]["compute"] != 999.0


# ---------------------------------------------------------- telemetry helpers


def test_chip_spec_lookup():
    assert chip_spec_for("TPU v5 lite").generation == "v5e"
    assert chip_spec_for("TPU v5p").generation == "v5p"
    assert chip_spec_for("TPU v4").generation == "v4"
    assert chip_spec_for("TPU v6 lite").generation == "v6e"
    assert chip_spec_for("cpu") is None
    assert chip_spec_for("unknown accelerator") is None


def test_model_cost_helpers():
    from llmlb_tpu.engine.presets import get_preset

    cfg = get_preset("debug-tiny")
    n_params = 1_000_000
    assert model_flops_per_token(cfg, n_params) == 2.0 * n_params
    # bytes: weights (amortized over batch) + KV reads for the context
    import jax.numpy as jnp

    itemsize = jnp.dtype(cfg.dtype).itemsize
    kv = cfg.num_layers * 64 * cfg.num_kv_heads * cfg.head_dim_ * 2 * itemsize
    assert model_bytes_per_token(cfg, n_params, 64, batch=1) == pytest.approx(
        n_params * itemsize + kv
    )
    assert model_bytes_per_token(cfg, n_params, 64, batch=4) == pytest.approx(
        n_params * itemsize / 4 + kv
    )
    # MoE: only routed experts count toward FLOPs
    moe = get_preset("debug-moe-tiny")
    dense_equiv = 2.0 * n_params
    assert model_flops_per_token(moe, n_params) < dense_equiv


# ------------------------------------------------------------------ e2e (CPU)


@pytest.fixture(scope="module")
def served_engine():
    from llmlb_tpu.engine.service import Engine

    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    yield engine
    engine.shutdown()


async def _run_requests(engine, n=3, max_tokens=8):
    from llmlb_tpu.engine.scheduler import SamplingParams

    for i in range(n):
        await engine.complete(
            [1 + i, 2, 3, 4, 5],
            SamplingParams(temperature=0.0, max_tokens=max_tokens),
        )


async def test_engine_steps_endpoint_and_phase_metrics(served_engine):
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app

    engine = served_engine
    await _run_requests(engine)
    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    try:
        resp = await client.get("/api/steps")
        assert resp.status == 200
        body = await resp.json()
        assert body["steps_total"] >= 3
        assert body["records"], body
        newest = body["records"][0]
        assert newest["kind"] in ("decode", "prefill")
        assert set(newest["phases_s"]) == set(PHASES)
        assert newest["total_s"] == pytest.approx(
            sum(newest["phases_s"].values()), abs=1e-5
        )
        kinds = {r["kind"] for r in body["records"]}
        assert "decode" in kinds and "prefill" in kinds
        # records are newest-first and sequences strictly decreasing
        seqs = [r["seq"] for r in body["records"]]
        assert seqs == sorted(seqs, reverse=True)
        assert "perf" in body and "ema_step_s" in body

        # limit + slow filters
        assert len((await (await client.get(
            "/api/steps?limit=2")).json())["records"]) == 2
        slow = await (await client.get("/api/steps?slow=1")).json()
        assert all(r["slow"] for r in slow["records"])
        assert (await client.get("/api/steps?limit=abc")).status == 400

        # /metrics carries the per-phase histograms with real samples
        text = await (await client.get("/metrics")).text()
        assert 'llmlb_engine_step_phase_seconds_count{phase="compute"}' in text
        compute_count = int(next(
            ln.rsplit(" ", 1)[1] for ln in text.splitlines()
            if ln.startswith(
                'llmlb_engine_step_phase_seconds_count{phase="compute"}')
        ))
        assert compute_count >= body["steps_total"] - 1
        assert "llmlb_engine_slow_steps_total" in text

        # CPU has no chip spec: perf block present, gauges absent
        system = await (await client.get("/api/system")).json()
        assert system["perf"]["available"] is False
        assert system["perf"]["flops_per_token"] > 0
        assert "llmlb_engine_mfu_ratio" not in text
    finally:
        await client.close()


async def test_instrumentation_overhead_under_one_percent(served_engine):
    """Acceptance: the full per-step recording path (StepRecorder.observe +
    EngineMetrics.record_step_phases) must cost < 1% of a measured engine
    step. Measured against the CPU debug engine's mean decode step — real
    TPU steps are orders of magnitude longer, so this is the conservative
    bound."""
    from llmlb_tpu.engine.metrics import EngineMetrics

    engine = served_engine
    await _run_requests(engine, n=2, max_tokens=16)
    hist = engine.core.metrics.decode_step
    assert hist.n > 0
    mean_step_s = hist.total / hist.n

    rec = StepRecorder()
    metrics = EngineMetrics()
    phases = {"plan": 1e-5, "host_sync": 1e-6, "dispatch": 1e-3,
              "compute": 1e-4, "fetch": 1e-4, "emit": 1e-4}
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        slow = rec.observe("decode", phases, active_slots=2, tokens=2)
        metrics.record_step_phases(phases, slow=slow)
    per_step = (time.perf_counter() - t0) / n
    # the timing side (10 perf_counter reads) is OS-cheap; bound the whole
    # record path against the measured mean step
    assert per_step < 0.01 * mean_step_s, (
        f"instrumentation {per_step * 1e6:.1f}µs/step vs mean step "
        f"{mean_step_s * 1e3:.3f}ms — over the 1% budget"
    )


# -------------------------------------------------------------- /api/profile


async def test_profile_capture_produces_downloadable_trace(tmp_path,
                                                           monkeypatch):
    """POST /api/profile start→stop on CPU JAX yields a completed capture
    whose zip artifact is non-empty and unpacks to real trace files."""
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    monkeypatch.setenv("LLMLB_TRACE_DIR", str(tmp_path))
    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    try:
        resp = await client.post("/api/profile",
                                 json={"action": "start", "seconds": 30})
        assert resp.status == 200
        started = await resp.json()
        capture_id = started["capture_id"]
        assert started["trace_dir"].startswith(str(tmp_path))

        # concurrent start refuses: the jax tracer is process-global
        dup = await client.post("/api/profile", json={"action": "start"})
        assert dup.status == 409

        status = await (await client.get("/api/profile")).json()
        assert status["recording"] is True

        # profile the serving loop itself so the trace has device events
        await _run_requests(engine, n=2)

        resp = await client.post("/api/profile", json={"action": "stop"})
        assert resp.status == 200
        done = await resp.json()
        assert done["capture_id"] == capture_id
        assert done["bytes"] > 0

        # double stop: nothing recording
        assert (await client.post(
            "/api/profile", json={"action": "stop"})).status == 409
        assert (await client.post(
            "/api/profile", json={"action": "nope"})).status == 400

        status = await (await client.get("/api/profile")).json()
        assert status["recording"] is False
        assert status["captures"][0]["capture_id"] == capture_id

        # the downloadable artifact: non-empty zip of real trace files
        art = await client.get(f"/api/profile/{capture_id}")
        assert art.status == 200
        assert art.headers["Content-Type"] == "application/zip"
        blob = await art.read()
        names = zipfile.ZipFile(io.BytesIO(blob)).namelist()
        assert names, "trace zip is empty"

        assert (await client.get("/api/profile/doesnotexist")).status == 404
    finally:
        await client.close()
        engine.shutdown()


async def test_profile_token_gates_every_route(monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    from llmlb_tpu.engine.server import create_engine_app
    from llmlb_tpu.engine.service import Engine

    monkeypatch.setenv("LLMLB_PROFILE_TOKEN", "s3cret")
    engine = Engine.from_preset(
        "debug-tiny", num_slots=2, slot_capacity=64, prefill_buckets=(16,)
    )
    client = TestClient(TestServer(create_engine_app(engine,
                                                     owns_engine=False)))
    await client.start_server()
    try:
        assert (await client.post(
            "/api/profile", json={"action": "start"})).status == 401
        assert (await client.get("/api/profile")).status == 401
        assert (await client.get("/api/profile/x")).status == 401
        assert (await client.post("/debug/profile", json={})).status == 401
        ok = await client.get(
            "/api/profile", headers={"Authorization": "Bearer s3cret"}
        )
        assert ok.status == 200
    finally:
        await client.close()
        engine.shutdown()


# ------------------------------------------------------------------ tpu probe


def test_staged_probe_timeout_preserves_child_evidence():
    """A hanging probe child is killed at the timeout and its stderr tail
    survives as evidence — the diagnosis plumbing for init hangs."""
    from llmlb_tpu.engine.tpu_probe import staged_probe

    hang = ("import sys, time\n"
            "print('[probe] stage1: hanging here', file=sys.stderr,"
            " flush=True)\n"
            "time.sleep(60)\n")
    ok, diag, evidence = staged_probe((1,), code=hang, log_fn=lambda m: None)
    assert ok is False
    assert "timed out" in diag
    rec = evidence["attempts"][0]
    assert "timeout" in rec["outcome"]
    assert any("hanging here" in ln for ln in rec["child_stderr_tail"])


def test_staged_probe_reports_non_tpu_backend():
    from llmlb_tpu.engine.tpu_probe import staged_probe

    fake = "print('cpu 1 cpu')\n"
    ok, diag, evidence = staged_probe((30,), code=fake, log_fn=lambda m: None)
    assert ok is False
    assert "not tpu" in diag
    assert evidence["attempts"][0]["outcome"].startswith("ok:")


def test_guard_backend_init_noop_without_tpu(monkeypatch):
    from llmlb_tpu.engine import tpu_probe

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    # would raise SystemExit if it probed and failed; must return instantly
    tpu_probe.guard_backend_init(1.0)
    # disabled guard never probes even when a TPU is "expected"
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    tpu_probe.guard_backend_init(0)


def test_guard_backend_init_fails_fast_on_hang(monkeypatch, capsys):
    from llmlb_tpu.engine import tpu_probe

    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setattr(
        tpu_probe, "PROBE_CODE",
        "import sys, time\n"
        "print('libtpu: claiming device', file=sys.stderr, flush=True)\n"
        "time.sleep(60)\n",
    )
    with pytest.raises(SystemExit) as exc:
        tpu_probe.guard_backend_init(1.0)
    assert "did not complete" in str(exc.value)
    err = capsys.readouterr().err
    assert "libtpu: claiming device" in err  # the captured child log tail
    assert "LLMLB_INIT_TIMEOUT=0" in err


def test_profile_wait_idle_wakes_on_stop_event_not_poll(tmp_path):
    """The /debug/profile wait path parks on the manager's idle event and
    wakes when the capture stops — the last 50 ms poll loop in a request
    path, now notify-based. Regression bound: wake latency well under one
    old poll tick."""
    import threading

    from llmlb_tpu.engine.profiling import ProfileManager

    mgr = ProfileManager(trace_root=str(tmp_path))
    assert mgr.wait_idle(0.01) is True  # idle from construction
    mgr.start(30)
    assert mgr.wait_idle(0.01) is False  # recording: the wait parks

    woke_after = {}

    def waiter():
        t0 = time.perf_counter()
        assert mgr.wait_idle(10.0) is True
        woke_after["s"] = time.perf_counter() - t0

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    t_stop = time.perf_counter()
    mgr.stop()
    t.join(timeout=5)
    stop_s = time.perf_counter() - t_stop
    assert not t.is_alive()
    # the waiter wakes with the stop itself, not a later poll tick; the
    # bound subtracts stop_trace's own serialization time
    assert woke_after["s"] - stop_s < 0.045, (
        f"wait_idle woke {woke_after['s'] * 1000:.1f}ms after a "
        f"{stop_s * 1000:.1f}ms stop — still polling?"
    )
