"""Engine-level structured outputs: constrained decoding through the real
scheduler + service on CPU JAX, seed reproducibility, violation accounting,
and the service.stream stop-sequence holdback edge at detok.flush()."""

import asyncio
import json
import queue

import jsonschema
import pytest

from llmlb_tpu.engine.scheduler import SamplingParams
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.engine.tokenizer import ByteTokenizer

SCHEMA = {
    "type": "object",
    "properties": {
        "ok": {"type": "boolean"},
        "tag": {"enum": ["alpha", "beta"]},
    },
    "required": ["ok", "tag"],
}


@pytest.fixture(scope="module")
def engine():
    eng = Engine.from_preset(
        "debug-tiny", model_id="tpu-structured", num_slots=4,
        slot_capacity=128, prefill_buckets=(16, 32, 64),
    )
    yield eng
    eng.shutdown()


def _chat_ids(engine, text="produce json"):
    return engine.encode_chat([{"role": "user", "content": text}])


def test_constrained_greedy_emits_schema_valid_json(engine):
    async def run():
        result = await engine.complete(
            _chat_ids(engine),
            SamplingParams(temperature=0.0, max_tokens=64,
                           constraint={"type": "json_schema",
                                       "schema": SCHEMA}),
        )
        assert result.finish_reason == "stop"
        jsonschema.validate(json.loads(result.text), SCHEMA)
    asyncio.run(run())


def test_constrained_stochastic_and_concurrent_mixed_batch(engine):
    """Constrained + free-form requests share the batch; every constrained
    stream must still be schema-valid with finish 'stop'."""
    async def run():
        constrained = [
            engine.complete(
                _chat_ids(engine, f"req {i}"),
                SamplingParams(temperature=1.0, max_tokens=64,
                               constraint={"type": "json_schema",
                                           "schema": SCHEMA}),
            )
            for i in range(3)
        ]
        free = [
            engine.complete(_chat_ids(engine, f"free {i}"),
                            SamplingParams(temperature=1.0, max_tokens=8))
            for i in range(3)
        ]
        results = await asyncio.gather(*constrained, *free)
        for r in results[:3]:
            assert r.finish_reason == "stop"
            jsonschema.validate(json.loads(r.text), SCHEMA)
        for r in results[3:]:
            assert r.finish_reason in ("stop", "length")
    asyncio.run(run())
    assert engine.core.metrics.structured_requests_total >= 3
    assert engine.core.metrics.masked_decode_steps_total > 0


def test_json_object_mode(engine):
    async def run():
        result = await engine.complete(
            _chat_ids(engine),
            SamplingParams(temperature=0.8, max_tokens=96,
                           constraint={"type": "json_object"}),
        )
        if result.finish_reason == "stop":
            assert isinstance(json.loads(result.text), dict)
        else:  # free-form object mode may hit max_tokens mid-string
            assert result.finish_reason == "length"
    asyncio.run(run())


def test_max_tokens_cut_counts_violation(engine):
    before = engine.core.metrics.constraint_violations_total

    async def run():
        result = await engine.complete(
            _chat_ids(engine),
            SamplingParams(temperature=0.9, max_tokens=2,
                           constraint={"type": "json_schema",
                                       "schema": SCHEMA}),
        )
        assert result.finish_reason == "length"
    asyncio.run(run())
    assert engine.core.metrics.constraint_violations_total > before


def test_invalid_constraint_rejected_before_submit(engine):
    async def run():
        with pytest.raises(ValueError) as exc:
            await engine.complete(
                _chat_ids(engine),
                SamplingParams(constraint={"type": "json_schema",
                                           "schema": {"allOf": []}}),
            )
        assert "allOf" in str(exc.value)
    asyncio.run(run())


def test_seed_reproducible_across_batches(engine):
    async def run():
        ids = _chat_ids(engine, "seeded run")
        params = SamplingParams(temperature=0.9, max_tokens=8, seed=1234)
        a = await engine.complete(ids, params)
        # same seed inside a busy batch must reproduce token for token
        noise = [
            engine.complete(_chat_ids(engine, f"noise {i}"),
                            SamplingParams(temperature=1.0, max_tokens=8))
            for i in range(3)
        ]
        b, *_ = await asyncio.gather(engine.complete(ids, params), *noise)
        c = await engine.complete(
            ids, SamplingParams(temperature=0.9, max_tokens=8, seed=77)
        )
        assert a.text == b.text
        assert a.text != c.text or a.text == ""  # different seed, new stream
    asyncio.run(run())


def test_constrained_compile_cache_reused(engine):
    info_before = engine.core.structured_info()

    async def run():
        for _ in range(2):
            await engine.complete(
                _chat_ids(engine),
                SamplingParams(temperature=0.0, max_tokens=64,
                               constraint={"type": "json_schema",
                                           "schema": SCHEMA}),
            )
    asyncio.run(run())
    info = engine.core.structured_info()
    assert info["compile_cache_hits"] > info_before["compile_cache_hits"]
    assert info["mask_cache_bytes"] > 0


# ------------------------------------------------- stop-holdback flush edge


class _ScriptedCore:
    """Stands in for EngineCore: plays a fixed token script into the request
    event queue so service.stream's holdback logic is tested byte-exactly."""

    num_slots = 2
    metrics = None
    constraint_compiler = None

    class cfg:
        vocab_size = 512

    def __init__(self, tokens):
        self._tokens = tokens

    def stop(self):
        pass

    def submit(self, request):
        for t in self._tokens:
            request.events.put(("token", t))
        request.events.put(("done", "stop"))
        return request


def _scripted_engine(tokens):
    return Engine("scripted", _ScriptedCore(tokens), ByteTokenizer(512))


def test_stop_completing_only_in_final_flush_truncates(monkeypatch):
    """A stop string whose last character only materializes in
    detok.flush() (a held-back split-UTF-8 byte decoding to U+FFFD) must
    still truncate, and nothing past the hit may ever be emitted."""
    # tokens: "ab" then "X" then a lone UTF-8 continuation head (0xC3).
    # push(0xC3) emits nothing (trailing U+FFFD held back); flush() emits
    # the replacement char, completing the stop "X�" only at flush.
    eng = _scripted_engine([ord("a"), ord("b"), ord("X"), 0xC3])

    async def run():
        deltas = []
        final = None
        async for delta in eng.stream([1], SamplingParams(max_tokens=8),
                                      stop=["X�"]):
            deltas.append(delta.text)
            if delta.finish_reason is not None:
                final = delta
        assert final is not None and final.finish_reason == "stop"
        text = "".join(deltas)
        assert text == "ab", repr(text)
        # holdback: no intermediate delta may have leaked the stop head "X"
        assert all("X" not in d for d in deltas), deltas
    asyncio.run(run())
    eng.shutdown()


def test_stop_at_position_zero_in_flush_emits_nothing():
    eng = _scripted_engine([ord("X"), 0xC3])

    async def run():
        collected = ""
        final = None
        async for delta in eng.stream([1], SamplingParams(max_tokens=8),
                                      stop=["X�"]):
            collected += delta.text
            if delta.finish_reason is not None:
                final = delta
        assert final is not None and final.finish_reason == "stop"
        assert collected == ""
    asyncio.run(run())
    eng.shutdown()


def test_stop_straddling_tokens_still_truncates_mid_stream():
    # control case: the classic straddle (no flush involvement) still works
    eng = _scripted_engine([ord("h"), ord("i"), ord("S"), ord("T"),
                            ord("z"), ord("z")])

    async def run():
        collected = ""
        final = None
        async for delta in eng.stream([1], SamplingParams(max_tokens=16),
                                      stop=["ST"]):
            collected += delta.text
            if delta.finish_reason is not None:
                final = delta
        assert final.finish_reason == "stop"
        assert collected == "hi"
    asyncio.run(run())
    eng.shutdown()
