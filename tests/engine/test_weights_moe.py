"""HF-checkpoint ingestion round-trip for the Mixtral (MoE) layout: write a
tiny HF-format checkpoint (config.json + safetensors with block_sparse_moe
names), load it back via engine.weights, and require exact param equality."""

import json

import jax
import numpy as np
from safetensors.numpy import save_file

from llmlb_tpu.engine.weights import load_checkpoint, load_config
from llmlb_tpu.models import mixtral


def _save_moe_checkpoint(tmp_path, cfg, params):
    def t(x):  # safetensors serializes raw buffers: transposes must be materialized
        return np.ascontiguousarray(np.asarray(x).T)

    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"]),
        "model.norm.weight": np.asarray(params["ln_final"]),
        "lm_head.weight": t(params["lm_head"]),
    }
    per_layer = {
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "block_sparse_moe.gate.weight": ("router", True),
        "input_layernorm.weight": ("ln_attn", False),
        "post_attention_layernorm.weight": ("ln_mlp", False),
    }
    for i in range(cfg.num_layers):
        for hf_name, (ours, transpose) in per_layer.items():
            w = np.asarray(params[ours][i])
            tensors[f"model.layers.{i}.{hf_name}"] = t(w) if transpose else w
        for e in range(cfg.num_experts):
            base = f"model.layers.{i}.block_sparse_moe.experts.{e}"
            tensors[f"{base}.w1.weight"] = t(params["we_gate"][i, e])
            tensors[f"{base}.w3.weight"] = t(params["we_up"][i, e])
            tensors[f"{base}.w2.weight"] = t(params["we_down"][i, e])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    hf_config = {
        "model_type": "mixtral",
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "num_local_experts": cfg.num_experts,
        "num_experts_per_tok": cfg.experts_per_token,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_eps,
        "max_position_embeddings": cfg.max_position_embeddings,
        "tie_word_embeddings": False,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_config))


def test_moe_checkpoint_roundtrip(tmp_path):
    from llmlb_tpu.engine.presets import get_preset

    cfg = get_preset("debug-moe-tiny")
    params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
    _save_moe_checkpoint(tmp_path, cfg, params)

    loaded_cfg = load_config(str(tmp_path), dtype=cfg.dtype)
    assert isinstance(loaded_cfg, mixtral.MixtralConfig)
    assert loaded_cfg.num_experts == cfg.num_experts
    assert loaded_cfg.experts_per_token == cfg.experts_per_token

    loaded = load_checkpoint(str(tmp_path), loaded_cfg)
    assert set(loaded) == set(params)
    for name in params:
        np.testing.assert_array_equal(
            np.asarray(loaded[name], np.float32),
            np.asarray(params[name], np.float32),
            err_msg=name,
        )
