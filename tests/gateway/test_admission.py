"""Notify-based admission queue: wake-on-release, FIFO order, timeouts.

Parity target: the reference's AdmissionDecision/WaitResult machinery
(balancer/mod.rs:2273-2427) — waiters are woken by lease releases, not polls.
"""

import asyncio
import time

from llmlb_tpu.gateway.balancer import AdmissionQueue, LoadManager
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind


def ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1")


def test_fast_path_admits_without_parking():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=2))
        q = AdmissionQueue(lm)
        a = ep("a")
        res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        assert res.admitted and res.endpoint is a and res.queue_position == 0
        assert lm.active_count(a.id) == 1
        res.lease.complete()
        assert lm.active_count(a.id) == 0

    asyncio.run(run())


def test_waiter_woken_by_release_not_poll():
    """A parked waiter proceeds as soon as the blocking lease releases —
    far faster than the old 50 ms poll tick."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        first = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        assert first.admitted

        async def waiter():
            return await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)

        task = asyncio.create_task(waiter())
        await asyncio.sleep(0.02)  # let it park
        assert q.queue_depth() == 1
        t0 = time.monotonic()
        first.lease.complete()
        second = await task
        wake_latency = time.monotonic() - t0
        assert second.admitted
        assert second.queue_position == 1
        assert wake_latency < 0.04, f"wake took {wake_latency * 1000:.1f}ms"
        second.lease.complete()

    asyncio.run(run())


def test_fifo_order_among_waiters():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        gatekeeper = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        order: list[int] = []

        async def waiter(i: int):
            res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)
            assert res.admitted
            order.append(i)
            await asyncio.sleep(0.01)
            res.lease.complete()

        tasks = []
        for i in range(3):
            tasks.append(asyncio.create_task(waiter(i)))
            await asyncio.sleep(0.01)  # deterministic arrival order
        assert q.queue_depth() == 3
        gatekeeper.lease.complete()
        await asyncio.gather(*tasks)
        assert order == [0, 1, 2]

    asyncio.run(run())


def test_timeout_reports_queue_position():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        hold = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        t0 = time.monotonic()
        res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=0.15)
        waited = time.monotonic() - t0
        assert not res.admitted
        assert res.queue_position == 1
        assert 0.1 < waited < 1.0
        assert q.queue_depth() == 0  # ticket cleaned up
        hold.lease.complete()

    asyncio.run(run())


def test_release_from_foreign_thread_wakes_waiter():
    """Leases can be released from non-loop threads (GC finalizer path);
    the wake must marshal onto the owning loop."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        hold = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)

        task = asyncio.create_task(
            q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        import threading

        threading.Thread(target=hold.lease.complete).start()
        res = await task
        assert res.admitted
        res.lease.complete()

    asyncio.run(run())


def test_registry_changes_picked_up_on_retry():
    """get_endpoints is re-invoked on wake: an endpoint added while parked
    can satisfy the waiter."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a, b = ep("a"), ep("b")
        pool = [a]
        hold = await q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=1.0)
        task = asyncio.create_task(
            q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        pool.append(b)  # new endpoint comes online while parked
        # a release on ANY endpoint triggers a retry, which now sees b
        dummy = lm.begin_request(a, "m", TpsApiKind.CHAT)
        dummy.fail()
        res = await task
        assert res.admitted and res.endpoint is b
        res.lease.complete()
        hold.lease.complete()

    asyncio.run(run())


def test_recheck_tick_notices_new_endpoint_without_release():
    """Capacity appearing WITHOUT a lease release (endpoint registered or
    recovered mid-wait) is noticed by the bounded safety tick."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a, b = ep("a"), ep("b")
        pool = [a]
        hold = await q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=1.0)
        task = asyncio.create_task(
            q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        pool.append(b)  # comes online; NO release ever fires
        res = await asyncio.wait_for(task, timeout=3.0)
        assert res.admitted and res.endpoint is b
        assert res.waited_s < 2.0  # one recheck tick, not the full timeout
        res.lease.complete()
        hold.lease.complete()

    asyncio.run(run())
