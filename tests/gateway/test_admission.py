"""Notify-based admission queue: wake-on-release, FIFO order, timeouts.

Parity target: the reference's AdmissionDecision/WaitResult machinery
(balancer/mod.rs:2273-2427) — waiters are woken by lease releases, not polls.
"""

import asyncio
import time

from llmlb_tpu.gateway.balancer import AdmissionQueue, LoadManager
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind


def ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1")


def test_fast_path_admits_without_parking():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=2))
        q = AdmissionQueue(lm)
        a = ep("a")
        res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        assert res.admitted and res.endpoint is a and res.queue_position == 0
        assert lm.active_count(a.id) == 1
        res.lease.complete()
        assert lm.active_count(a.id) == 0

    asyncio.run(run())


def test_waiter_woken_by_release_not_poll():
    """A parked waiter proceeds as soon as the blocking lease releases —
    far faster than the old 50 ms poll tick."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        first = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        assert first.admitted

        async def waiter():
            return await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)

        task = asyncio.create_task(waiter())
        await asyncio.sleep(0.02)  # let it park
        assert q.queue_depth() == 1
        t0 = time.monotonic()
        first.lease.complete()
        second = await task
        wake_latency = time.monotonic() - t0
        assert second.admitted
        assert second.queue_position == 1
        assert wake_latency < 0.04, f"wake took {wake_latency * 1000:.1f}ms"
        second.lease.complete()

    asyncio.run(run())


def test_fifo_order_among_waiters():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        gatekeeper = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        order: list[int] = []

        async def waiter(i: int):
            res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)
            assert res.admitted
            order.append(i)
            await asyncio.sleep(0.01)
            res.lease.complete()

        tasks = []
        for i in range(3):
            tasks.append(asyncio.create_task(waiter(i)))
            await asyncio.sleep(0.01)  # deterministic arrival order
        assert q.queue_depth() == 3
        gatekeeper.lease.complete()
        await asyncio.gather(*tasks)
        assert order == [0, 1, 2]

    asyncio.run(run())


def test_timeout_reports_queue_position():
    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        hold = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
        t0 = time.monotonic()
        res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=0.15)
        waited = time.monotonic() - t0
        assert not res.admitted
        assert res.queue_position == 1
        assert 0.1 < waited < 1.0
        assert q.queue_depth() == 0  # ticket cleaned up
        hold.lease.complete()

    asyncio.run(run())


def test_release_from_foreign_thread_wakes_waiter():
    """Leases can be released from non-loop threads (GC finalizer path);
    the wake must marshal onto the owning loop."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a = ep("a")
        hold = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)

        task = asyncio.create_task(
            q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        import threading

        threading.Thread(target=hold.lease.complete).start()
        res = await task
        assert res.admitted
        res.lease.complete()

    asyncio.run(run())


def test_registry_changes_picked_up_on_retry():
    """get_endpoints is re-invoked on wake: an endpoint added while parked
    can satisfy the waiter."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a, b = ep("a"), ep("b")
        pool = [a]
        hold = await q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=1.0)
        task = asyncio.create_task(
            q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        pool.append(b)  # new endpoint comes online while parked
        # a release on ANY endpoint triggers a retry, which now sees b
        dummy = lm.begin_request(a, "m", TpsApiKind.CHAT)
        dummy.fail()
        res = await task
        assert res.admitted and res.endpoint is b
        res.lease.complete()
        hold.lease.complete()

    asyncio.run(run())


def test_queue_wait_latency_regression_end_to_end():
    """Latency regression over the full select_endpoint_with_queue path (the
    handler-facing wrapper around the AdmissionQueue): a parked request must
    admit within a release-notification latency — far under one 50 ms poll
    tick — and the queue-timeout path must keep its semantics (QueueTimeout
    carrying position + waited_s, which the handlers turn into 503 +
    Retry-After)."""
    import pytest

    from llmlb_tpu.gateway.api_openai import (
        QueueTimeout,
        select_endpoint_with_queue,
    )
    from llmlb_tpu.gateway.types import Capability

    class _Registry:
        def __init__(self, endpoint):
            self.endpoint = endpoint

        def find_by_model(self, model, capability=None):
            class _M:
                model_id = "m"
            return [(self.endpoint, _M())]

    class _Metrics:
        def record_queue_wait(self, *a):
            pass

        def record_queue_timeout(self, *a):
            pass

        def record_retry(self, *a):
            pass

    class _State:
        pass

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1,
                                     queue_timeout_s=5.0))
        state = _State()
        state.load_manager = lm
        state.admission = AdmissionQueue(lm)
        state.admission.metrics = None
        state.registry = _Registry(ep("a"))
        state.metrics = _Metrics()

        # saturate the single admission slot
        first = await select_endpoint_with_queue(
            state, "m", Capability.CHAT_COMPLETION, TpsApiKind.CHAT
        )
        assert first is not None
        _, _, lease, _ = first

        async def parked():
            return await select_endpoint_with_queue(
                state, "m", Capability.CHAT_COMPLETION, TpsApiKind.CHAT
            )

        task = asyncio.create_task(parked())
        await asyncio.sleep(0.02)
        t0 = time.monotonic()
        lease.complete()
        second = await task
        wake_ms = (time.monotonic() - t0) * 1000
        assert second is not None
        assert wake_ms < 40, f"queue-wait wake took {wake_ms:.1f}ms"
        second[2].complete()

        # timeout semantics intact: position + waited_s reach the handler
        blocker = await select_endpoint_with_queue(
            state, "m", Capability.CHAT_COMPLETION, TpsApiKind.CHAT
        )
        with pytest.raises(QueueTimeout) as exc:
            await select_endpoint_with_queue(
                state, "m", Capability.CHAT_COMPLETION, TpsApiKind.CHAT,
                queue_timeout_s=0.1,
            )
        assert exc.value.queue_position == 1
        assert exc.value.waited_s >= 0.1
        blocker[2].complete()

    asyncio.run(run())


def test_recheck_tick_notices_new_endpoint_without_release():
    """Capacity appearing WITHOUT a lease release (endpoint registered or
    recovered mid-wait) is noticed by the bounded safety tick."""

    async def run():
        lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
        q = AdmissionQueue(lm)
        a, b = ep("a"), ep("b")
        pool = [a]
        hold = await q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=1.0)
        task = asyncio.create_task(
            q.admit(lambda: pool, "m", TpsApiKind.CHAT, timeout_s=5.0)
        )
        await asyncio.sleep(0.02)
        pool.append(b)  # comes online; NO release ever fires
        res = await asyncio.wait_for(task, timeout=3.0)
        assert res.admitted and res.endpoint is b
        assert res.waited_s < 2.0  # one recheck tick, not the full timeout
        res.lease.complete()
        hold.lease.complete()

    asyncio.run(run())
