"""Anthropic /v1/messages adapter: conversion both ways + SSE transform."""

import asyncio
import json

from llmlb_tpu.gateway.api_anthropic import (
    AnthropicStreamEncoder,
    anthropic_request_to_openai,
    openai_response_to_anthropic,
)
from tests.support import (
    GatewayHarness,
    MockOpenAIEndpoint,
    assert_sse_protocol,
)


def test_request_conversion_messages_and_system():
    body = {
        "model": "m", "max_tokens": 50,
        "system": "be helpful",
        "messages": [
            {"role": "user", "content": "hi"},
            {"role": "assistant", "content": [
                {"type": "text", "text": "hello"},
                {"type": "tool_use", "id": "tu1", "name": "get_weather",
                 "input": {"city": "SF"}},
            ]},
            {"role": "user", "content": [
                {"type": "tool_result", "tool_use_id": "tu1",
                 "content": [{"type": "text", "text": "sunny"}]},
            ]},
        ],
        "stop_sequences": ["END"],
        "temperature": 0.5,
        "tools": [{"name": "get_weather", "description": "w",
                   "input_schema": {"type": "object"}}],
        "tool_choice": {"type": "auto"},
    }
    out = anthropic_request_to_openai(body)
    assert out["messages"][0] == {"role": "system", "content": "be helpful"}
    assert out["messages"][1] == {"role": "user", "content": "hi"}
    asst = out["messages"][2]
    assert asst["role"] == "assistant"
    assert asst["tool_calls"][0]["function"]["name"] == "get_weather"
    assert json.loads(asst["tool_calls"][0]["function"]["arguments"]) == {
        "city": "SF"}
    tool_msg = out["messages"][3]
    assert tool_msg["role"] == "tool" and tool_msg["content"] == "sunny"
    assert out["stop"] == ["END"]
    assert out["tools"][0]["function"]["name"] == "get_weather"
    assert out["tool_choice"] == "auto"


def test_response_conversion_with_tool_calls():
    openai_resp = {
        "choices": [{
            "finish_reason": "tool_calls",
            "message": {
                "role": "assistant", "content": "let me check",
                "tool_calls": [{
                    "id": "call_1", "type": "function",
                    "function": {"name": "f", "arguments": '{"a": 1}'},
                }],
            },
        }],
        "usage": {"prompt_tokens": 10, "completion_tokens": 4},
    }
    out = openai_response_to_anthropic(openai_resp, "m")
    assert out["stop_reason"] == "tool_use"
    types = [b["type"] for b in out["content"]]
    assert types == ["text", "tool_use"]
    assert out["content"][1]["input"] == {"a": 1}
    assert out["usage"] == {"input_tokens": 10, "output_tokens": 4}


def _event_names(bs):
    return [
        line.split(": ", 1)[1]
        for b in bs
        for line in b.decode().splitlines()
        if line.startswith("event: ")
    ]


def _payloads(bs):
    return [
        json.loads(line.split(": ", 1)[1])
        for b in bs
        for line in b.decode().splitlines()
        if line.startswith("data: ")
    ]


def test_stream_encoder_event_sequence():
    enc = AnthropicStreamEncoder("m", input_token_estimate=9)
    events, payloads = [], []

    def push(bs):
        events.extend(_event_names(bs))
        payloads.extend(_payloads(bs))

    push(enc.feed({
        "choices": [{"delta": {"role": "assistant", "content": "he"}}]}))
    push(enc.feed({"choices": [{"delta": {"content": "y"}}]}))
    push(enc.feed({
        "choices": [{"delta": {"tool_calls": [{
            "index": 0, "id": "c1",
            "function": {"name": "f", "arguments": ""}}]}}]}))
    push(enc.feed({
        "choices": [{"delta": {"tool_calls": [{
            "index": 0, "function": {"arguments": '{"x":1}'}}]},
            "finish_reason": "tool_calls"}]}))
    push(enc.feed({
        "choices": [], "usage": {"prompt_tokens": 5, "completion_tokens": 3}}))
    push(enc.finish())

    assert events[0] == "message_start"
    assert payloads[0]["message"]["usage"]["input_tokens"] == 9  # estimate
    assert "content_block_start" in events
    assert "content_block_delta" in events
    # text block closes before the tool_use block opens
    first_stop = events.index("content_block_stop")
    second_start = events.index("content_block_start", first_stop)
    assert second_start > first_stop
    assert events[-2:] == ["message_delta", "message_stop"]
    md = [p for p in payloads if p.get("type") == "message_delta"][0]
    assert md["usage"] == {"output_tokens": 3, "input_tokens": 5}  # reported
    tool_start = [p for p in payloads
                  if p.get("type") == "content_block_start"
                  and p["content_block"]["type"] == "tool_use"][0]
    assert tool_start["content_block"]["name"] == "f"


def test_stream_encoder_interleaved_parallel_tool_calls():
    """Fragments of two tools interleaved by index must not splice JSON."""
    enc = AnthropicStreamEncoder("m")
    out = []
    out += enc.feed({"choices": [{"delta": {"tool_calls": [
        {"index": 0, "id": "a", "function": {"name": "fa", "arguments": '{"a"'}},
    ]}}]})
    out += enc.feed({"choices": [{"delta": {"tool_calls": [
        {"index": 1, "id": "b", "function": {"name": "fb", "arguments": '{"b"'}},
    ]}}]})
    out += enc.feed({"choices": [{"delta": {"tool_calls": [
        {"index": 0, "function": {"arguments": ': 1}'}},
        {"index": 1, "function": {"arguments": ': 2}'}},
    ]}}]})
    out += enc.finish()
    payloads = _payloads(out)
    deltas = [p for p in payloads if p.get("type") == "content_block_delta"]
    blocks = [p for p in payloads if p.get("type") == "content_block_start"]
    by_index = {}
    for d in deltas:
        by_index.setdefault(d["index"], []).append(d["delta"]["partial_json"])
    names = {b["index"]: b["content_block"]["name"] for b in blocks}
    joined = {names[i]: json.loads("".join(frags))
              for i, frags in by_index.items()}
    assert joined == {"fa": {"a": 1}, "fb": {"b": 2}}


def test_messages_endpoint_non_stream_and_stream():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="mock-model").start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()

            # non-stream
            r = await gw.client.post("/v1/messages", json={
                "model": "mock-model", "max_tokens": 32,
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["type"] == "message"
            assert body["role"] == "assistant"
            assert body["content"][0]["type"] == "text"
            assert body["usage"]["output_tokens"] == 5
            assert body["stop_reason"] == "end_turn"

            # validation: max_tokens required
            r = await gw.client.post("/v1/messages", json={
                "model": "mock-model",
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 400
            assert (await r.json())["type"] == "error"

            # stream: full anthropic event sequence
            r = await gw.client.post("/v1/messages", json={
                "model": "mock-model", "max_tokens": 32, "stream": True,
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 200
            raw = (await r.read()).decode()
            assert_sse_protocol(raw.encode(), "anthropic")
            event_names = [l.split(": ", 1)[1] for l in raw.splitlines()
                           if l.startswith("event: ")]
            assert event_names[0] == "message_start"
            assert "content_block_delta" in event_names
            assert event_names[-1] == "message_stop"
            # usage flowed into message_delta
            deltas = [json.loads(l[6:]) for l in raw.splitlines()
                      if l.startswith("data: ")]
            md = [d for d in deltas if d.get("type") == "message_delta"][0]
            assert md["usage"]["output_tokens"] == 5

            # x-api-key header auth (Anthropic SDK style)
            key = await gw.inference_key()
            r = await gw.client.post("/v1/messages", json={
                "model": "mock-model", "max_tokens": 8,
                "messages": [{"role": "user", "content": "hi"}],
            }, headers={"x-api-key": key})
            assert r.status == 200
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_speculative_knob_rides_the_anthropic_conversion():
    """Per-request speculative-decoding knobs must reach the engine through
    BOTH dialects; the Anthropic adapter carries them verbatim."""
    body = {
        "model": "m", "max_tokens": 16,
        "messages": [{"role": "user", "content": "hi"}],
        "speculative": {"enabled": True, "max_draft_tokens": 6},
    }
    out = anthropic_request_to_openai(body)
    assert out["speculative"] == {"enabled": True, "max_draft_tokens": 6}
    # absent stays absent — no key invented for engines that predate it
    assert "speculative" not in anthropic_request_to_openai(
        {"model": "m", "max_tokens": 16,
         "messages": [{"role": "user", "content": "hi"}]}
    )
