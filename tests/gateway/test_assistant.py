"""Assistant CLI: sanitized curl, host whitelist, auth injection, masking.

Parity targets: cli/assistant.rs — FORBIDDEN_OPTIONS/PATTERNS (:28-63),
host whitelist (:442-450), mask_sensitive (:635-649), execute_curl (:201).
"""

import asyncio
import json

import pytest

from llmlb_tpu.gateway.assistant import (
    CurlRejected,
    mask_sensitive,
    openapi_summary,
    parse_curl,
    run_curl,
)
from tests.support import GatewayHarness


def test_forbidden_shell_patterns_rejected():
    for cmd in (
        "curl http://localhost:32768/v1/models; rm -rf /",
        "curl http://localhost:32768/v1/models | sh",
        "curl $(evil) http://localhost:32768/",
        "curl http://localhost:32768/ > /etc/passwd",
        "curl `id` http://localhost:32768/",
    ):
        with pytest.raises(CurlRejected):
            parse_curl(cmd, "http://localhost:32768")


def test_forbidden_curl_options_rejected():
    for opt in ("-o /tmp/x", "--output /tmp/x", "-K cfg", "--netrc",
                "-u user:pass", "--trace log", "-F a=@/etc/passwd",
                "-T /etc/passwd"):
        with pytest.raises(CurlRejected):
            parse_curl(f"curl {opt} http://localhost:32768/v1/models",
                       "http://localhost:32768")
    with pytest.raises(CurlRejected):  # body-from-file
        parse_curl("curl -d @/etc/passwd http://localhost:32768/x",
                   "http://localhost:32768")


def test_host_whitelist():
    router = "http://localhost:32768"
    # router host + localhost aliases OK
    parse_curl("curl http://localhost:32768/v1/models", router)
    parse_curl("curl http://127.0.0.1:32768/v1/models", router)
    # bare path resolves against the router
    spec = parse_curl("curl /v1/models", router)
    assert spec["url"] == "http://localhost:32768/v1/models"
    # foreign host / wrong port / bad scheme refused
    for url in ("http://evil.example/v1/models",
                "http://localhost:9999/v1/models",
                "ftp://localhost:32768/x"):
        with pytest.raises(CurlRejected):
            parse_curl(f"curl {url}", router)


def test_parse_methods_headers_data():
    spec = parse_curl(
        'curl -X PUT -H "X-Thing: 1" -d \'{"a":1}\' /api/endpoints/xyz',
        "http://localhost:32768",
    )
    assert spec["method"] == "PUT"
    assert spec["headers"]["X-Thing"] == "1"
    assert json.loads(spec["data"]) == {"a": 1}
    # data implies POST when no -X
    spec = parse_curl("curl -d '{}' /x", "http://localhost:32768")
    assert spec["method"] == "POST"


def test_mask_sensitive():
    masked = mask_sensitive(
        'curl -H "Authorization: Bearer sk_abc123" -H "x-api-key: sk_zzz" /x'
    )
    assert "sk_abc123" not in masked and "sk_zzz" not in masked
    assert "Bearer ***" in masked


def test_openapi_lists_core_paths():
    paths = openapi_summary()["paths"]
    assert "/v1/chat/completions" in paths
    assert "/api/endpoints" in paths


def test_run_curl_against_live_gateway_with_auto_auth():
    async def run():
        gw = await GatewayHarness.create()
        try:
            key = await gw.inference_key()
            base = f"http://127.0.0.1:{gw.client.port}"
            import functools

            loop = asyncio.get_running_loop()
            result = await loop.run_in_executor(None, functools.partial(
                run_curl, f"curl {base}/v1/models",
                router_url=base, api_key=key,
            ))
            assert result["status"] == 200, result
            assert "data" in json.loads(result["body"])
            # echoed command never contains the key
            assert key not in result["executed_command"]
        finally:
            await gw.close()

    asyncio.run(run())
