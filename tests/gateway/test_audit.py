"""Audit hash chain: append, verify, tamper detection, archive re-anchor."""

import time

from llmlb_tpu.gateway.audit import AuditEntry, AuditLog
from llmlb_tpu.gateway.db import Database


def entry(path="/v1/chat/completions", status=200) -> AuditEntry:
    return AuditEntry(
        ts=time.time(), method="POST", path=path, status=status,
        duration_ms=1.2, actor="admin", actor_type="jwt", ip="127.0.0.1",
    )


def test_chain_verifies_and_detects_tampering():
    db = Database(":memory:")
    log = AuditLog(db)
    for batch in range(3):
        for _ in range(4):
            log.record(entry())
        log.flush()
    ok, err = log.verify()
    assert ok, err

    # tamper with a persisted entry
    db.execute("UPDATE audit_log SET status=500 WHERE id=5")
    ok, err = log.verify()
    assert not ok
    assert "hash mismatch" in err


def test_chain_detects_deleted_entry():
    db = Database(":memory:")
    log = AuditLog(db)
    for _ in range(6):
        log.record(entry())
    log.flush()
    db.execute("DELETE FROM audit_log WHERE id=2")
    ok, err = log.verify()
    assert not ok


def test_search_filters():
    db = Database(":memory:")
    log = AuditLog(db)
    log.record(entry(path="/api/endpoints"))
    log.record(entry(path="/v1/chat/completions", status=502))
    log.flush()
    assert len(log.search(path_prefix="/api")) == 1
    assert len(log.search(q="chat")) == 1
    assert len(log.search()) == 2


def test_archive_reanchors_chain(tmp_path):
    db = Database(":memory:")
    log = AuditLog(db)
    old = AuditEntry(ts=time.time() - 100 * 86400, method="GET", path="/old",
                     status=200, duration_ms=1)
    log.record(old)
    log.flush()
    for _ in range(3):
        log.record(entry())
    log.flush()

    archive_path = str(tmp_path / "archive.db")
    moved = log.archive_older_than(time.time() - 90 * 86400, archive_path)
    assert moved == 1
    ok, err = log.verify()
    assert ok, err

    import sqlite3
    arch = sqlite3.connect(archive_path)
    assert arch.execute("SELECT COUNT(*) FROM audit_log").fetchone()[0] == 1


def test_fts_search_and_injection_safety():
    db = Database(":memory:")
    assert db.fts_enabled
    log = AuditLog(db)
    log.record(AuditEntry(ts=time.time(), method="POST", path="/api/users",
                          status=201, duration_ms=1, actor="alice",
                          detail="created user bob"))
    log.record(AuditEntry(ts=time.time(), method="POST", path="/api/endpoints",
                          status=201, duration_ms=1, actor="carol",
                          detail="registered tpu endpoint"))
    log.flush()
    # token match across columns
    assert len(log.search(q="bob")) == 1
    assert len(log.search(q="alice")) == 1
    # multi-term AND semantics
    assert len(log.search(q="created bob")) == 1
    assert len(log.search(q="created carol")) == 0
    # FTS operators must be inert user text, not syntax errors
    for hostile in ('NEAR(', 'a AND b OR', '"unbalanced', 'path:*', '^x'):
        log.search(q=hostile)  # must not raise
    # whitespace-only query is a no-filter search
    assert len(log.search(q="   ")) == 2


def test_fts_stays_in_sync_with_deletes(tmp_path):
    db = Database(":memory:")
    log = AuditLog(db)
    log.record(AuditEntry(ts=time.time() - 100 * 86400, method="GET",
                          path="/ancient", status=200, duration_ms=1))
    log.flush()
    log.record(AuditEntry(ts=time.time(), method="GET", path="/fresh",
                          status=200, duration_ms=1))
    log.flush()
    log.archive_older_than(time.time() - 90 * 86400,
                           str(tmp_path / "archive.db"))
    # the delete trigger removed the archived row from the index
    assert len(log.search(q="ancient")) == 0
    assert len(log.search(q="fresh")) == 1


def test_search_like_fallback_when_fts_unavailable():
    db = Database(":memory:")
    db.fts_enabled = False  # simulate a sqlite build without fts5
    log = AuditLog(db)
    log.record(AuditEntry(ts=time.time(), method="POST", path="/api/users",
                          status=201, duration_ms=1, detail="made bob"))
    log.flush()
    assert len(log.search(q="bob")) == 1
    assert len(log.search(q="nope")) == 0


def test_fts_backfill_on_upgrade(tmp_path):
    """A DB created before the FTS table must be backfilled at open, or
    archive deletes corrupt the external-content index."""
    import sqlite3

    path = str(tmp_path / "old.db")
    conn = sqlite3.connect(path)
    conn.executescript("""
        CREATE TABLE audit_log (
            id INTEGER PRIMARY KEY AUTOINCREMENT, ts REAL NOT NULL,
            method TEXT NOT NULL, path TEXT NOT NULL, status INTEGER NOT NULL,
            duration_ms REAL NOT NULL, actor TEXT, actor_type TEXT, ip TEXT,
            detail TEXT, batch_id INTEGER);
    """)
    conn.execute(
        "INSERT INTO audit_log (ts,method,path,status,duration_ms,detail) "
        "VALUES (?,?,?,?,?,?)",
        (time.time() - 100 * 86400, "GET", "/prehistoric", 200, 1.0, "old row"),
    )
    conn.commit()
    conn.close()

    db = Database(path)
    log = AuditLog(db)
    # pre-existing row is searchable (backfill ran)
    assert len(log.search(q="prehistoric")) == 1
    # and archiving it does not corrupt the index
    log.archive_older_than(time.time() - 90 * 86400,
                           str(tmp_path / "arch.db"))
    assert len(log.search(q="prehistoric")) == 0
    db.execute("INSERT INTO audit_log_fts(audit_log_fts) VALUES('integrity-check')")
