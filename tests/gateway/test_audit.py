"""Audit hash chain: append, verify, tamper detection, archive re-anchor."""

import time

from llmlb_tpu.gateway.audit import AuditEntry, AuditLog
from llmlb_tpu.gateway.db import Database


def entry(path="/v1/chat/completions", status=200) -> AuditEntry:
    return AuditEntry(
        ts=time.time(), method="POST", path=path, status=status,
        duration_ms=1.2, actor="admin", actor_type="jwt", ip="127.0.0.1",
    )


def test_chain_verifies_and_detects_tampering():
    db = Database(":memory:")
    log = AuditLog(db)
    for batch in range(3):
        for _ in range(4):
            log.record(entry())
        log.flush()
    ok, err = log.verify()
    assert ok, err

    # tamper with a persisted entry
    db.execute("UPDATE audit_log SET status=500 WHERE id=5")
    ok, err = log.verify()
    assert not ok
    assert "hash mismatch" in err


def test_chain_detects_deleted_entry():
    db = Database(":memory:")
    log = AuditLog(db)
    for _ in range(6):
        log.record(entry())
    log.flush()
    db.execute("DELETE FROM audit_log WHERE id=2")
    ok, err = log.verify()
    assert not ok


def test_search_filters():
    db = Database(":memory:")
    log = AuditLog(db)
    log.record(entry(path="/api/endpoints"))
    log.record(entry(path="/v1/chat/completions", status=502))
    log.flush()
    assert len(log.search(path_prefix="/api")) == 1
    assert len(log.search(q="chat")) == 1
    assert len(log.search()) == 2


def test_archive_reanchors_chain(tmp_path):
    db = Database(":memory:")
    log = AuditLog(db)
    old = AuditEntry(ts=time.time() - 100 * 86400, method="GET", path="/old",
                     status=200, duration_ms=1)
    log.record(old)
    log.flush()
    for _ in range(3):
        log.record(entry())
    log.flush()

    archive_path = str(tmp_path / "archive.db")
    moved = log.archive_older_than(time.time() - 90 * 86400, archive_path)
    assert moved == 1
    ok, err = log.verify()
    assert ok, err

    import sqlite3
    arch = sqlite3.connect(archive_path)
    assert arch.execute("SELECT COUNT(*) FROM audit_log").fetchone()[0] == 1
