"""Auth: JWT sign/verify, password policy, API keys, invitations, bootstrap."""

import time

import pytest

from llmlb_tpu.gateway.auth import (
    ApiKeyStore,
    AuthError,
    InvitationStore,
    UserStore,
    create_jwt,
    ensure_admin_exists,
    hash_password,
    validate_password_policy,
    verify_jwt,
    verify_password,
)
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.types import Permission, Role


@pytest.fixture
def db():
    return Database(":memory:")


def test_jwt_roundtrip():
    token = create_jwt("secret", "u1", "alice", Role.ADMIN)
    payload = verify_jwt("secret", token)
    assert payload["sub"] == "u1"
    assert payload["role"] == "admin"


def test_jwt_bad_signature_and_expiry():
    token = create_jwt("secret", "u1", "alice", Role.VIEWER)
    with pytest.raises(AuthError):
        verify_jwt("other-secret", token)
    expired = create_jwt("secret", "u1", "alice", Role.VIEWER,
                         ttl_s=10, now=time.time() - 100)
    with pytest.raises(AuthError):
        verify_jwt("secret", expired)
    with pytest.raises(AuthError):
        verify_jwt("secret", "not.a.token")
    # alg tampering (e.g. alg=none) must be rejected
    import base64, json
    header = base64.urlsafe_b64encode(
        json.dumps({"alg": "none", "typ": "JWT"}).encode()
    ).rstrip(b"=").decode()
    parts = token.split(".")
    with pytest.raises(AuthError):
        verify_jwt("secret", f"{header}.{parts[1]}.{parts[2]}")


def test_password_hash_and_policy():
    h = hash_password("s3cretpw1")
    assert verify_password(h, "s3cretpw1")
    assert not verify_password(h, "wrong")
    with pytest.raises(AuthError):
        validate_password_policy("short1")
    with pytest.raises(AuthError):
        validate_password_policy("nodigitshere")
    validate_password_policy("goodpass1")


def test_user_store_and_bootstrap_admin(db):
    users = UserStore(db)
    admin, generated = ensure_admin_exists(users)
    assert generated is not None
    assert admin.role == Role.ADMIN
    # second call: no-op
    again, gen2 = ensure_admin_exists(users)
    assert gen2 is None and again.id == admin.id
    assert users.authenticate("admin", generated).id == admin.id
    assert users.authenticate("admin", "wrong") is None

    users.change_password(admin.id, "newpass99")
    assert users.authenticate("admin", "newpass99") is not None
    assert not users.get(admin.id).must_change_password


def test_api_keys(db):
    users = UserStore(db)
    u = users.create("bob", "password1", Role.VIEWER)
    keys = ApiKeyStore(db)
    record, raw = keys.create(u.id, "test", [Permission.OPENAI_INFERENCE])
    assert raw.startswith("sk_")
    verified = keys.verify(raw)
    assert verified is not None
    assert Permission.OPENAI_INFERENCE in verified.permissions
    assert keys.verify("sk_bogus") is None
    keys.revoke(record.id)
    assert keys.verify(raw) is None
    # expired key
    _, raw2 = keys.create(u.id, "old", [], expires_at=time.time() - 10)
    assert keys.verify(raw2) is None


def test_invitations(db):
    users = UserStore(db)
    admin = users.create("root", "password1", Role.ADMIN)
    invs = InvitationStore(db)
    inv = invs.create(admin.id, Role.VIEWER)
    new_user = invs.redeem(inv["code"], "carol", "password1", users)
    assert new_user.role == Role.VIEWER
    with pytest.raises(AuthError):
        invs.redeem(inv["code"], "dave", "password1", users)  # reuse
    with pytest.raises(AuthError):
        invs.redeem("nope", "dave", "password1", users)
