"""LoadManager: TPS EMA, selection order, leases, history buckets."""

import gc
import time

from llmlb_tpu.gateway.balancer import (
    METRICS_STALE_S,
    TELEMETRY_MIN_PENALTY,
    TPS_EMA_ALPHA,
    LoadManager,
    ModelTpsState,
    RequestRecord,
    telemetry_penalty,
)
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import AcceleratorInfo, Endpoint, TpsApiKind


def ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1234")


def test_ema_alpha():
    s = ModelTpsState()
    s.update(100, 1.0)  # first sample: exact
    assert s.ema_tps == 100.0
    s.update(200, 1.0)
    assert abs(s.ema_tps - (TPS_EMA_ALPHA * 200 + (1 - TPS_EMA_ALPHA) * 100)) < 1e-9
    s.update(0, 1.0)  # zero tokens ignored
    assert s.samples == 2


def test_selection_prefers_higher_tps_and_probes_unmeasured():
    lm = LoadManager()
    a, b, c = ep("a"), ep("b"), ep("c")
    lm.update_tps(a.id, "m", TpsApiKind.CHAT, 100, 1.0)  # 100 tps
    lm.update_tps(b.id, "m", TpsApiKind.CHAT, 300, 1.0)  # 300 tps
    # c unmeasured -> +inf score, must be probed first
    assert lm.select_endpoint([a, b, c], "m") is c
    lm.update_tps(c.id, "m", TpsApiKind.CHAT, 10, 1.0)
    assert lm.select_endpoint([a, b, c], "m") is b


def test_round_robin_tie_break():
    lm = LoadManager()
    endpoints = [ep("a"), ep("b"), ep("c")]  # all unmeasured: tie
    picks = [lm.select_endpoint(endpoints, "m").name for _ in range(6)]
    assert picks == ["a", "b", "c", "a", "b", "c"]


def test_admission_cap_excludes_full_endpoints():
    lm = LoadManager(QueueConfig(max_active_per_endpoint=2))
    a, b = ep("a"), ep("b")
    leases = [lm.begin_request(a, "m", TpsApiKind.CHAT) for _ in range(2)]
    assert lm.select_endpoint([a, b], "m") is b
    lease_b = [lm.begin_request(b, "m", TpsApiKind.CHAT) for _ in range(2)]
    assert lm.select_endpoint([a, b], "m") is None
    leases[0].complete()
    assert lm.select_endpoint([a, b], "m") is a
    for l in leases[1:] + lease_b:
        l.fail()


def test_lease_complete_with_tokens_updates_tps():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    assert lm.active_count(a.id) == 1
    lease.complete_with_tokens(10, 50)
    assert lm.active_count(a.id) == 0
    assert lm.get_tps(a.id, "m", TpsApiKind.CHAT) is not None


def test_lease_drop_releases():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    assert lm.active_count(a.id) == 1
    del lease
    gc.collect()
    assert lm.active_count(a.id) == 0


def test_double_release_is_idempotent():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    lease.complete()
    lease.fail()
    assert lm.active_count(a.id) == 0


def test_clear_tps_for_endpoint():
    lm = LoadManager()
    a = ep("a")
    lm.update_tps(a.id, "m", TpsApiKind.CHAT, 100, 1.0)
    lm.clear_tps_for_endpoint(a.id)
    assert lm.get_tps(a.id, "m", TpsApiKind.CHAT) is None


def test_history_minute_buckets():
    lm = LoadManager()
    now = time.time()
    for i in range(5):
        lm.record_request(RequestRecord(
            ts=now, endpoint_id="e", model="m", api_kind=TpsApiKind.CHAT,
            status_code=200 if i % 2 == 0 else 500, duration_ms=10,
            prompt_tokens=5, completion_tokens=7,
        ))
    buckets = lm.history_minute_buckets()
    assert sum(b["requests"] for b in buckets) == 5
    assert sum(b["errors"] for b in buckets) == 2
    assert sum(b["completion_tokens"] for b in buckets) == 35

# ---------------------------------------------------- telemetry-aware placement

def tpu_ep(name: str, *, hbm_used=0, hbm_total=0, queued=0) -> Endpoint:
    e = ep(name)
    e.accelerator = AcceleratorInfo(
        accelerator="tpu", chip_count=1,
        hbm_used_bytes=hbm_used, hbm_total_bytes=hbm_total,
        queue_depth=queued, num_slots=8, sampled_at=time.time(),
    )
    return e


def test_telemetry_penalty_shape():
    assert telemetry_penalty(ep("plain")) == 1.0  # no telemetry -> neutral
    low = tpu_ep("low", hbm_used=50, hbm_total=100)
    assert telemetry_penalty(low) == 1.0  # below the knee -> neutral
    hot = tpu_ep("hot", hbm_used=99, hbm_total=100)
    assert telemetry_penalty(hot) < 0.15
    full = tpu_ep("full", hbm_used=100, hbm_total=100)
    assert abs(telemetry_penalty(full) - TELEMETRY_MIN_PENALTY) < 1e-9
    queued = tpu_ep("queued", queued=3)
    assert abs(telemetry_penalty(queued) - 0.25) < 1e-9


def test_hbm_pressured_endpoint_deprioritized():
    """Two TPU endpoints, equal measured TPS; the HBM-pressured one loses."""
    lm = LoadManager()
    calm = tpu_ep("calm", hbm_used=40, hbm_total=100)
    hot = tpu_ep("hot", hbm_used=97, hbm_total=100)
    for e in (calm, hot):
        lm.update_tps(e.id, "m", TpsApiKind.CHAT, 200, 1.0)
    for _ in range(4):
        assert lm.select_endpoint([hot, calm], "m") is calm


def test_engine_queue_depth_deprioritized():
    lm = LoadManager()
    idle = tpu_ep("idle")
    backed_up = tpu_ep("backed", queued=5)
    for e in (idle, backed_up):
        lm.update_tps(e.id, "m", TpsApiKind.CHAT, 200, 1.0)
    for _ in range(4):
        assert lm.select_endpoint([backed_up, idle], "m") is idle


def test_unmeasured_tie_broken_by_telemetry_then_rr():
    lm = LoadManager()
    hot = tpu_ep("hot", hbm_used=99, hbm_total=100)
    a, b = tpu_ep("a"), tpu_ep("b")
    # all unmeasured (inf): the pressured one must not be probed first
    picks = [lm.select_endpoint([hot, a, b], "m").name for _ in range(4)]
    assert "hot" not in picks
    assert picks == ["a", "b", "a", "b"]  # RR among the healthy pair


def test_telemetry_does_not_flip_large_tps_gap():
    """A mildly queued endpoint that is 10x faster still wins."""
    lm = LoadManager()
    fast = tpu_ep("fast", queued=1)      # penalty 0.5
    slow = tpu_ep("slow")
    lm.update_tps(fast.id, "m", TpsApiKind.CHAT, 1000, 1.0)
    lm.update_tps(slow.id, "m", TpsApiKind.CHAT, 100, 1.0)
    assert lm.select_endpoint([fast, slow], "m") is fast


def test_stale_telemetry_is_ignored():
    """A snapshot older than METRICS_STALE_S must not demote an endpoint."""
    stale = tpu_ep("stale", hbm_used=99, hbm_total=100, queued=9)
    stale.accelerator.sampled_at = time.time() - METRICS_STALE_S - 1
    assert telemetry_penalty(stale) == 1.0
    never = tpu_ep("never", hbm_used=99, hbm_total=100)
    never.accelerator.sampled_at = 0.0  # never sampled (e.g. built from DB row)
    assert telemetry_penalty(never) == 1.0
