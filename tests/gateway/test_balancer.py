"""LoadManager: TPS EMA, selection order, leases, history buckets."""

import gc
import time

from llmlb_tpu.gateway.balancer import (
    TPS_EMA_ALPHA,
    LoadManager,
    ModelTpsState,
    RequestRecord,
)
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind


def ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1234")


def test_ema_alpha():
    s = ModelTpsState()
    s.update(100, 1.0)  # first sample: exact
    assert s.ema_tps == 100.0
    s.update(200, 1.0)
    assert abs(s.ema_tps - (TPS_EMA_ALPHA * 200 + (1 - TPS_EMA_ALPHA) * 100)) < 1e-9
    s.update(0, 1.0)  # zero tokens ignored
    assert s.samples == 2


def test_selection_prefers_higher_tps_and_probes_unmeasured():
    lm = LoadManager()
    a, b, c = ep("a"), ep("b"), ep("c")
    lm.update_tps(a.id, "m", TpsApiKind.CHAT, 100, 1.0)  # 100 tps
    lm.update_tps(b.id, "m", TpsApiKind.CHAT, 300, 1.0)  # 300 tps
    # c unmeasured -> +inf score, must be probed first
    assert lm.select_endpoint([a, b, c], "m") is c
    lm.update_tps(c.id, "m", TpsApiKind.CHAT, 10, 1.0)
    assert lm.select_endpoint([a, b, c], "m") is b


def test_round_robin_tie_break():
    lm = LoadManager()
    endpoints = [ep("a"), ep("b"), ep("c")]  # all unmeasured: tie
    picks = [lm.select_endpoint(endpoints, "m").name for _ in range(6)]
    assert picks == ["a", "b", "c", "a", "b", "c"]


def test_admission_cap_excludes_full_endpoints():
    lm = LoadManager(QueueConfig(max_active_per_endpoint=2))
    a, b = ep("a"), ep("b")
    leases = [lm.begin_request(a, "m", TpsApiKind.CHAT) for _ in range(2)]
    assert lm.select_endpoint([a, b], "m") is b
    lease_b = [lm.begin_request(b, "m", TpsApiKind.CHAT) for _ in range(2)]
    assert lm.select_endpoint([a, b], "m") is None
    leases[0].complete()
    assert lm.select_endpoint([a, b], "m") is a
    for l in leases[1:] + lease_b:
        l.fail()


def test_lease_complete_with_tokens_updates_tps():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    assert lm.active_count(a.id) == 1
    lease.complete_with_tokens(10, 50)
    assert lm.active_count(a.id) == 0
    assert lm.get_tps(a.id, "m", TpsApiKind.CHAT) is not None


def test_lease_drop_releases():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    assert lm.active_count(a.id) == 1
    del lease
    gc.collect()
    assert lm.active_count(a.id) == 0


def test_double_release_is_idempotent():
    lm = LoadManager()
    a = ep("a")
    lease = lm.begin_request(a, "m", TpsApiKind.CHAT)
    lease.complete()
    lease.fail()
    assert lm.active_count(a.id) == 0


def test_clear_tps_for_endpoint():
    lm = LoadManager()
    a = ep("a")
    lm.update_tps(a.id, "m", TpsApiKind.CHAT, 100, 1.0)
    lm.clear_tps_for_endpoint(a.id)
    assert lm.get_tps(a.id, "m", TpsApiKind.CHAT) is None


def test_history_minute_buckets():
    lm = LoadManager()
    now = time.time()
    for i in range(5):
        lm.record_request(RequestRecord(
            ts=now, endpoint_id="e", model="m", api_kind=TpsApiKind.CHAT,
            status_code=200 if i % 2 == 0 else 500, duration_ms=10,
            prompt_tokens=5, completion_tokens=7,
        ))
    buckets = lm.history_minute_buckets()
    assert sum(b["requests"] for b in buckets) == 5
    assert sum(b["errors"] for b in buckets) == 2
    assert sum(b["completion_tokens"] for b in buckets) == 35
