"""Tier-1 chaos drill: REAL engine processes, real SIGKILL/SIGTERM.

Drives scripts/bench_gateway.run_chaos_engine_kill in-process: the real
gateway in front of two spawned engine-server processes (CPU backend,
seed-0 weights), 8 streams mid-generation, then (a) SIGKILL the busiest
engine — every cut stream must resume token-identically on the survivor —
and (b) SIGTERM + drain another — zero client-visible errors. The
mock-level unit tests live in test_stream_resume.py; this is the
end-to-end proof against real process death.

The drill's observability twin rides the same run: engines share a
flight-recorder spool (LLMLB_FLIGHTREC_SPOOL), so after each drill the
gateway's /api/traces/{id}?view=timeline merge is checked for every
resumed stream — events from BOTH engine processes, causally ordered,
with no gap past the cut (docs/tracing.md).
"""

import asyncio
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "scripts"))


@pytest.fixture(scope="module")
def drill_result():
    import bench_gateway

    return asyncio.run(bench_gateway.run_chaos_engine_kill(streams=8))


def test_chaos_engine_kill_and_drain(drill_result):
    result = drill_result
    assert result["passed"], result

    kill = result["drills"]["sigkill"]
    assert kill["success_rate"] >= 0.99, result
    assert kill["token_identical"] == kill["client_success"], result

    drain = result["drills"]["sigterm_drain"]
    assert drain["success_rate"] >= 0.99, result
    assert drain["errors"] == [], result  # zero client-visible errors
    assert drain["token_identical"] == drain["client_success"], result

    # non-vacuous: streams were actually cut and actually resumed
    assert result["stream_interruptions"] >= 1, result
    assert result["stream_resumes"].get("success", 0) >= 1, result
    assert result["stream_resumed_tokens"] >= 0


def test_chaos_merged_timeline_spans_both_engines(drill_result):
    """PR 16 twin: a SIGKILL-resumed stream's merged timeline must carry
    flight-recorder events from BOTH engine processes — the victim's via
    the shared spool — in causal order (no survivor event before the
    cut, a terminal event past it)."""
    kill_tl = drill_result["drills"]["sigkill"]["timeline"]
    assert kill_tl["resumed_verified"] >= 1, drill_result
    assert kill_tl["failures"] == [], drill_result
    # checked == every stream the gateway recorded a resume for
    assert kill_tl["checked"] == kill_tl["resumed_verified"], drill_result

    # the drain drill parks instead of dying; its resumed streams must
    # merge just as cleanly (park on the victim, adopt on the survivor)
    drain_tl = drill_result["drills"]["sigterm_drain"]["timeline"]
    assert drain_tl["failures"] == [], drill_result
