"""Tier-1 chaos drill: REAL engine processes, real SIGKILL/SIGTERM.

Drives scripts/bench_gateway.run_chaos_engine_kill in-process: the real
gateway in front of two spawned engine-server processes (CPU backend,
seed-0 weights), 8 streams mid-generation, then (a) SIGKILL the busiest
engine — every cut stream must resume token-identically on the survivor —
and (b) SIGTERM + drain another — zero client-visible errors. The
mock-level unit tests live in test_stream_resume.py; this is the
end-to-end proof against real process death.
"""

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "scripts"))


def test_chaos_engine_kill_and_drain():
    import bench_gateway

    result = asyncio.run(bench_gateway.run_chaos_engine_kill(streams=8))
    assert result["passed"], result

    kill = result["drills"]["sigkill"]
    assert kill["success_rate"] >= 0.99, result
    assert kill["token_identical"] == kill["client_success"], result

    drain = result["drills"]["sigterm_drain"]
    assert drain["success_rate"] >= 0.99, result
    assert drain["errors"] == [], result  # zero client-visible errors
    assert drain["token_identical"] == drain["client_success"], result

    # non-vacuous: streams were actually cut and actually resumed
    assert result["stream_interruptions"] >= 1, result
    assert result["stream_resumes"].get("success", 0) >= 1, result
    assert result["stream_resumed_tokens"] >= 0
