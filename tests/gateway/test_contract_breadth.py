"""Contract breadth (VERDICT r2 item 10): queueing edges, streaming
abort/Drop safety, and the full per-scope API-key permission matrix.

Parity targets: reference tests/contract/queueing_test.rs behaviors,
api/proxy.rs Drop-safe lease finalization, common/auth.rs permission scopes.
"""

import asyncio

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from tests.support import GatewayHarness, MockOpenAIEndpoint

# ------------------------------------------------------------- queueing edges


def _tune_queue(gw, **overrides) -> None:
    import dataclasses

    lm = gw.state.load_manager
    lm.queue_config = dataclasses.replace(lm.queue_config, **overrides)


def test_queue_timeout_503_reports_position():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m", reply_delay_s=2.0).start()
        try:
            gw.register_mock(mock.url, ["m"])
            _tune_queue(gw, max_active_per_endpoint=1, queue_timeout_s=0.3)
            headers = await gw.inference_headers()

            async def one():
                return await gw.client.post("/v1/chat/completions", json={
                    "model": "m", "messages": [{"role": "user", "content": "x"}],
                }, headers=headers)

            first = asyncio.create_task(one())
            await asyncio.sleep(0.1)  # occupies the only slot
            second = await one()
            assert second.status == 503
            body = await second.json()
            assert "position" in body["error"]["message"]
            r1 = await first
            assert r1.status == 200
        finally:
            await mock.stop()
            await gw.close()

    asyncio.run(run())


def test_queued_request_admits_when_slot_frees():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m", reply_delay_s=0.4).start()
        try:
            gw.register_mock(mock.url, ["m"])
            _tune_queue(gw, max_active_per_endpoint=1, queue_timeout_s=10.0)
            headers = await gw.inference_headers()

            async def one():
                r = await gw.client.post("/v1/chat/completions", json={
                    "model": "m", "messages": [{"role": "user", "content": "x"}],
                }, headers=headers)
                assert r.status == 200, await r.text()

            await asyncio.gather(*(one() for _ in range(3)))
            # all three landed on the endpoint, strictly serialized
            assert len(mock.requests_seen) == 3
            assert gw.state.load_manager.total_active() == 0
        finally:
            await mock.stop()
            await gw.close()

    asyncio.run(run())


# --------------------------------------------------- streaming abort safety


class HangingStreamEndpoint(MockOpenAIEndpoint):
    """Streams one chunk then stalls until cancelled — a wedged upstream."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.aborted = asyncio.Event()

    async def start(self):
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/chat/completions", self._hang)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _hang(self, request):
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"}
        )
        await resp.prepare(request)
        await resp.write(b'data: {"choices":[{"index":0,'
                         b'"delta":{"content":"x"}}]}\n\n')
        try:
            await asyncio.sleep(3600)
        except (asyncio.CancelledError, ConnectionResetError):
            self.aborted.set()
            raise
        return resp


def test_client_abort_mid_stream_releases_lease():
    """Drop safety (api/proxy.rs:186-204 parity): a client vanishing mid-SSE
    must release the endpoint's active slot so later requests are admitted."""

    async def run():
        gw = await GatewayHarness.create()
        hang = await HangingStreamEndpoint(model="m").start()
        try:
            ep = gw.register_mock(hang.url, ["m"], name="hang")
            _tune_queue(gw, max_active_per_endpoint=1)
            headers = await gw.inference_headers()

            async def aborted_stream():
                resp = await gw.client.post("/v1/chat/completions", json={
                    "model": "m", "stream": True,
                    "messages": [{"role": "user", "content": "x"}],
                }, headers=headers)
                assert resp.status == 200
                await resp.content.read(10)  # first bytes arrive...
                resp.close()  # ...then the client drops the connection

            await aborted_stream()
            await asyncio.wait_for(hang.aborted.wait(), timeout=10)
            # the lease must drain back to zero so the slot is reusable
            for _ in range(100):
                if gw.state.load_manager.active_count(ep.id) == 0:
                    break
                await asyncio.sleep(0.05)
            assert gw.state.load_manager.active_count(ep.id) == 0
        finally:
            await hang.stop()
            await gw.close()

    asyncio.run(run())


# ------------------------------------------------- per-scope permission matrix

# (method, path, body) probes per permission scope; each must be allowed with
# the scope and denied without it (403), mirroring common/auth.rs:59-97.
_MATRIX = [
    ("openai.inference", "POST", "/v1/chat/completions",
     {"model": "m", "messages": [{"role": "user", "content": "x"}]}),
    ("openai.models.read", "GET", "/v1/models", None),
    ("endpoints.read", "GET", "/api/endpoints", None),
    ("endpoints.manage", "POST", "/api/endpoints",
     {"base_url": "http://127.0.0.1:9", "endpoint_type": "openai_compatible"}),
    ("logs.read", "GET", "/api/audit-log", None),
    ("logs.read", "GET", "/api/dashboard/logs/lb", None),
    ("metrics.read", "GET", "/api/dashboard/overview", None),
    ("metrics.read", "GET", "/api/metrics/cloud", None),
    ("registry.read", "GET", "/api/models/registry/some-model/manifest.json",
     None),
    ("invitations.manage", "POST", "/api/invitations", {"role": "viewer"}),
    ("users.manage", "GET", "/api/users", None),
]


@pytest.mark.parametrize("perm,method,path,body", _MATRIX)
def test_api_key_permission_matrix(perm, method, path, body):
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(mock.url, ["m"])
            admin = await gw.admin_headers()

            async def key_with(perms: list[str]) -> dict:
                resp = await gw.client.post(
                    "/api/api-keys", json={"name": "k", "permissions": perms},
                    headers=admin,
                )
                assert resp.status == 201
                return {
                    "Authorization":
                        f"Bearer {(await resp.json())['api_key']}"
                }

            granted = await key_with([perm])
            resp = await gw.client.request(
                method, path, json=body, headers=granted
            )
            # may 404/502 on missing data, but NEVER 401/403
            assert resp.status not in (401, 403), (
                perm, path, resp.status, await resp.text()
            )

            # a disjoint scope must be denied
            other = ("metrics.read" if perm != "metrics.read"
                     else "endpoints.read")
            denied = await key_with([other])
            resp = await gw.client.request(
                method, path, json=body, headers=denied
            )
            assert resp.status == 403, (perm, path, resp.status)
        finally:
            await mock.stop()
            await gw.close()

    asyncio.run(run())


def test_inference_scope_grants_models_read():
    """openai.inference implies the models listing (reference behavior:
    inference keys can discover what to call)."""

    async def run():
        gw = await GatewayHarness.create()
        try:
            headers = await gw.inference_headers()
            resp = await gw.client.get("/v1/models", headers=headers)
            assert resp.status == 200
        finally:
            await gw.close()

    asyncio.run(run())


async def test_request_history_redacts_inline_media():
    """The reference's sanitization contract (openai_request_sanitization_
    spec.rs, shipped ignored there): inline base64 media must never land in
    request_history; text content and structure must survive."""
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="mm-model").start()
    try:
        gw.register_mock(upstream.url, ["mm-model"])
        headers = await gw.inference_headers()
        sensitive_image = "SENSITIVE_IMAGE_BASE64_" + "A" * 600
        sensitive_audio = "SENSITIVE_AUDIO_BASE64_" + "B" * 600
        resp = await gw.client.post("/v1/chat/completions", json={
            "model": "mm-model",
            "stream": False,
            "messages": [{
                "role": "user",
                "content": [
                    {"type": "text", "text": "describe this"},
                    {"type": "image_url",
                     "image_url": {"url": f"data:image/png;base64,{sensitive_image}"}},
                    {"type": "input_audio",
                     "input_audio": {"data": sensitive_audio, "format": "wav"}},
                ],
            }],
        }, headers=headers)
        assert resp.status == 200, await resp.text()

        admin = await gw.admin_headers()
        resp = await gw.client.get("/api/dashboard/requests", headers=admin)
        records = (await resp.json())["records"]
        assert records, "no request history record written"
        detail = await gw.client.get(
            f"/api/dashboard/requests/{records[0]['id']}", headers=admin
        )
        row = await detail.json()
        stored = row["request_body"]
        assert stored, "request_body not stored"
        assert sensitive_image not in stored
        assert sensitive_audio not in stored
        assert "describe this" in stored  # prompt text survives
        assert "<redacted" in stored
        assert "data:image/png" in stored  # media TYPE survives for debugging
    finally:
        await upstream.stop()
        await gw.close()


def test_sanitizer_edge_cases():
    """Responses-API string-form media, malformed data: URLs, byte-bounded
    truncation, and non-base64 'data' values (review findings, pinned)."""
    import json as _json

    from llmlb_tpu.gateway.sanitize import (
        MAX_STORED_BODY_BYTES,
        sanitize_request_body,
    )

    b64 = "A" * 600
    # string-form image_url and file_data (Responses API shapes)
    out = sanitize_request_body({
        "input": [
            {"type": "input_image", "image_url": f"data:image/png;base64,{b64}"},
            {"type": "input_file", "file_data": f"data:application/pdf;base64,{b64}"},
        ],
    })
    assert b64 not in out and out.count("<redacted") == 2

    # malformed data: URL with no comma must not leak through the 'head'
    out = sanitize_request_body({"url": "data:image/png;base64" + b64})
    assert b64 not in out and "<redacted" in out

    # long plain-text under a generic 'data' key survives (not base64)
    prose = ("this is a long plain text tool payload, with spaces and "
             "punctuation! " * 8)
    out = sanitize_request_body({"data": prose})
    assert prose in out

    # base64-looking payload under 'data' is redacted
    out = sanitize_request_body({"data": b64})
    assert b64 not in out

    # truncation is byte-bounded even for multi-byte text
    big = {"text": "漢" * 40_000}  # ~120KB utf-8
    out = sanitize_request_body(big)
    assert len(out.encode()) < 2 * MAX_STORED_BODY_BYTES
    parsed = _json.loads(out)
    assert parsed["_truncated"] is True
    assert parsed["_original_bytes"] > MAX_STORED_BODY_BYTES


async def test_client_ip_alert_threshold():
    """Client analytics flags IPs whose last-hour request count reaches the
    configurable ip_alert_threshold; invalid threshold writes are rejected
    (reference clients_alert_test T052/T053, dashboard.rs:1265-1379)."""
    import time as _time
    import uuid as _uuid

    from tests.support import GatewayHarness

    gw = await GatewayHarness.create()
    try:
        admin = await gw.admin_headers()
        # default threshold is 100
        resp = await gw.client.get("/api/dashboard/clients", headers=admin)
        assert (await resp.json())["ip_alert_threshold"] == 100

        # invalid writes are 400; valid writes apply
        for bad in ("0", "-3", "abc"):
            resp = await gw.client.put(
                "/api/dashboard/settings",
                json={"key": "ip_alert_threshold", "value": bad},
                headers=admin,
            )
            assert resp.status == 400, bad
        resp = await gw.client.put(
            "/api/dashboard/settings",
            json={"key": "ip_alert_threshold", "value": "5"},
            headers=admin,
        )
        assert resp.status == 200

        # IP-A: 10 requests in the last hour (over); IP-B: 2 (under);
        # IP-C: exactly 5 (at threshold -> alert, >= semantics)
        now = _time.time()
        for ip, n in (("10.0.0.1", 10), ("10.0.0.2", 2), ("10.0.0.3", 5)):
            for i in range(n):
                gw.state.db.execute(
                    """INSERT INTO request_history
                       (id, ts, model, api_kind, path, status_code,
                        duration_ms, prompt_tokens, completion_tokens,
                        client_ip, stream)
                       VALUES (?,?,?,?,?,?,?,?,?,?,0)""",
                    (_uuid.uuid4().hex, now - 60 * i, "m", "chat", "/x",
                     200, 1.0, 1, 1, ip),
                )
        resp = await gw.client.get("/api/dashboard/clients", headers=admin)
        body = await resp.json()
        flags = {r["client_ip"]: r["is_alert"] for r in body["ranking"]}
        assert flags["10.0.0.1"] is True
        assert flags["10.0.0.2"] is False
        assert flags["10.0.0.3"] is True  # >= threshold
        assert body["ip_alert_threshold"] == 5
    finally:
        await gw.close()
