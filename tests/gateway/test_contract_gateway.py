"""Gateway contract tests: API shapes through the real middleware stack.

Mirrors the reference's contract tier (llmlb/tests/contract/, SURVEY.md §4):
real app + in-memory DB + mock upstream endpoints.
"""

import asyncio
import json

from tests.support import (
    GatewayHarness,
    MockOpenAIEndpoint,
    assert_sse_protocol,
)


def test_auth_contract():
    async def run():
        gw = await GatewayHarness.create()
        try:
            # unauthenticated /v1 -> 401 OpenAI-style error
            r = await gw.client.post("/v1/chat/completions", json={"model": "x"})
            assert r.status == 401
            body = await r.json()
            assert body["error"]["type"] == "authentication_error"

            # unauthenticated admin -> 401
            r = await gw.client.get("/api/endpoints")
            assert r.status == 401

            # bad login
            r = await gw.client.post("/api/auth/login", json={
                "username": "admin", "password": "wrong"})
            assert r.status == 401

            # good login + me
            headers = await gw.admin_headers()
            r = await gw.client.get("/api/auth/me", headers=headers)
            assert r.status == 200
            assert (await r.json())["role"] == "admin"

            # api key without inference permission is rejected on /v1
            r = await gw.client.post(
                "/api/api-keys",
                json={"name": "limited", "permissions": ["metrics.read"]},
                headers=headers,
            )
            limited = (await r.json())["api_key"]
            r = await gw.client.post(
                "/v1/chat/completions", json={"model": "x"},
                headers={"Authorization": f"Bearer {limited}"},
            )
            assert r.status == 403
        finally:
            await gw.close()
    asyncio.run(run())


def test_viewer_role_is_read_only():
    async def run():
        gw = await GatewayHarness.create()
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/users", json={
                "username": "viewer1", "password": "viewerpw1",
                "role": "viewer"}, headers=headers)
            assert r.status == 201
            r = await gw.client.post("/api/auth/login", json={
                "username": "viewer1", "password": "viewerpw1"})
            vtoken = (await r.json())["token"]
            vheaders = {"Authorization": f"Bearer {vtoken}"}

            r = await gw.client.get("/api/endpoints", headers=vheaders)
            assert r.status == 200
            r = await gw.client.post("/api/endpoints", json={
                "base_url": "http://127.0.0.1:1"}, headers=vheaders)
            assert r.status == 403
            # self-service is allowed
            r = await gw.client.post(
                "/api/api-keys", json={"name": "mine"}, headers=vheaders)
            assert r.status == 201
        finally:
            await gw.close()
    asyncio.run(run())


def test_chat_completion_proxy_non_stream():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="mock-model").start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "mock-model",
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["choices"][0]["message"]["content"].startswith("tok0")
            assert body["usage"]["completion_tokens"] == 5

            # unknown model -> 404
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "nope", "messages": []}, headers=headers)
            assert r.status == 404
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_chat_completion_proxy_stream_passthrough_and_tps():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="mock-model").start()
        try:
            ep = gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "mock-model", "stream": True,
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 200
            raw = (await r.read()).decode()
            assert "tok0" in raw and raw.strip().endswith("data: [DONE]")
            assert_sse_protocol(raw.encode(), "openai")
            # stream_options.include_usage was injected toward upstream
            assert mock.requests_seen[-1]["stream_options"]["include_usage"]

            # TPS got recorded from the stream's usage chunk
            from llmlb_tpu.gateway.types import TpsApiKind
            await asyncio.sleep(0.05)
            tps = gw.state.load_manager.get_tps(
                ep.id, "mock-model", TpsApiKind.CHAT)
            assert tps is not None and tps > 0
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_responses_and_embeddings_and_models():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="mock-model").start()
        try:
            from llmlb_tpu.gateway.types import Capability
            gw.register_mock(
                mock.url, ["mock-model"],
                capabilities=[Capability.CHAT_COMPLETION],
            )
            gw.register_mock(
                mock.url + "/", ["embed-model"], name="emb",
                capabilities=[Capability.EMBEDDINGS],
            ) if False else None
            headers = await gw.inference_headers()

            r = await gw.client.post("/v1/responses", json={
                "model": "mock-model", "input": "hello"}, headers=headers)
            assert r.status == 200

            r = await gw.client.get("/v1/models", headers=headers)
            models = (await r.json())["data"]
            assert any(m["id"] == "mock-model" for m in models)

            r = await gw.client.get("/v1/models/mock-model", headers=headers)
            assert r.status == 200

            # embeddings require the capability: mock-model doesn't have it
            r = await gw.client.post("/v1/embeddings", json={
                "model": "mock-model", "input": "x"}, headers=headers)
            assert r.status == 404
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_upstream_error_normalized_to_502():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(fail_with=500).start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "mock-model", "messages": []}, headers=headers)
            assert r.status == 502
            body = await r.json()
            assert body["error"]["type"] == "server_error"
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_unreachable_endpoint_502():
    async def run():
        gw = await GatewayHarness.create()
        try:
            gw.register_mock("http://127.0.0.1:1", ["dead-model"])
            headers = await gw.inference_headers()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "dead-model", "messages": []}, headers=headers)
            assert r.status == 502
        finally:
            await gw.close()
    asyncio.run(run())


def test_endpoint_admin_crud_and_audit():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/endpoints", json={
                "base_url": mock.url, "name": "mock1"}, headers=headers)
            assert r.status == 201, await r.text()
            created = await r.json()
            assert created["endpoint_type"] == "openai_compatible"

            r = await gw.client.get("/api/endpoints", headers=headers)
            eps = (await r.json())["endpoints"]
            assert len(eps) == 1

            eid = created["id"]
            r = await gw.client.post(
                f"/api/endpoints/{eid}/test", headers=headers)
            assert (await r.json())["ok"] is True

            r = await gw.client.put(f"/api/endpoints/{eid}", json={
                "name": "renamed"}, headers=headers)
            assert (await r.json())["name"] == "renamed"

            r = await gw.client.delete(f"/api/endpoints/{eid}", headers=headers)
            assert r.status == 200

            # audit captured all of that
            gw.state.audit.flush()
            r = await gw.client.get(
                "/api/audit-log?path=/api/endpoints", headers=headers)
            entries = (await r.json())["entries"]
            assert len(entries) >= 4
            r = await gw.client.post("/api/audit-log/verify", headers=headers)
            assert (await r.json())["ok"] is True
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_dashboard_apis():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            iheaders = await gw.inference_headers()
            for _ in range(3):
                await gw.client.post("/v1/chat/completions", json={
                    "model": "mock-model",
                    "messages": [{"role": "user", "content": "hi"}],
                }, headers=iheaders)
            headers = await gw.admin_headers()

            r = await gw.client.get("/api/dashboard/overview", headers=headers)
            ov = await r.json()
            assert ov["requests"]["today"] == 3
            assert ov["endpoints"]["online"] == 1

            r = await gw.client.get(
                "/api/dashboard/request-history", headers=headers)
            minutes = (await r.json())["minutes"]
            assert sum(m["requests"] for m in minutes) == 3

            r = await gw.client.get("/api/dashboard/requests", headers=headers)
            records = (await r.json())["records"]
            assert len(records) == 3
            detail = await gw.client.get(
                f"/api/dashboard/requests/{records[0]['id']}", headers=headers)
            assert detail.status == 200

            r = await gw.client.get(
                "/api/dashboard/token-stats", headers=headers)
            stats = await r.json()
            assert stats["total"]["requests"] == 3
            assert stats["by_model"][0]["model"] == "mock-model"

            r = await gw.client.get("/api/dashboard/clients", headers=headers)
            assert r.status == 200

            r = await gw.client.get("/api/system", headers=headers)
            assert (await r.json())["name"] == "llmlb_tpu"
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_dashboard_websocket_receives_events():
    async def run():
        gw = await GatewayHarness.create()
        try:
            token = await gw.admin_token()
            ws = await gw.client.ws_connect(f"/ws/dashboard?token={token}")
            gw.state.events.publish("MetricsUpdated", {"x": 1})
            msg = await asyncio.wait_for(ws.receive(), timeout=5)
            event = json.loads(msg.data)
            assert event["type"] == "MetricsUpdated"
            await ws.close()

            # viewer is rejected
            headers = await gw.admin_headers()
            await gw.client.post("/api/users", json={
                "username": "v2", "password": "viewerpw1", "role": "viewer",
            }, headers=headers)
            r = await gw.client.post("/api/auth/login", json={
                "username": "v2", "password": "viewerpw1"})
            vtoken = (await r.json())["token"]
            try:
                await gw.client.ws_connect(f"/ws/dashboard?token={vtoken}")
                assert False, "viewer WS should be rejected"
            except Exception:
                pass
        finally:
            await gw.close()
    asyncio.run(run())


def test_update_drain_gate():
    """During drain /v1/* returns 503 + Retry-After (reference §3.4)."""
    async def run():
        from llmlb_tpu.gateway.update import UpdateManager

        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.state.update_manager = UpdateManager(
                gw.state.gate, gw.state.events, drain_timeout_s=1.0)
            gw.register_mock(mock.url, ["mock-model"])
            iheaders = await gw.inference_headers()
            aheaders = await gw.admin_headers()

            gw.state.gate.start_rejecting()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "mock-model", "messages": []}, headers=iheaders)
            assert r.status == 503
            assert r.headers["Retry-After"] == "30"
            # admin surface still reachable during drain
            r = await gw.client.get("/api/system", headers=aheaders)
            assert r.status == 200
            gw.state.gate.stop_rejecting()

            r = await gw.client.post("/v1/chat/completions", json={
                "model": "mock-model",
                "messages": [{"role": "user", "content": "x"}],
            }, headers=iheaders)
            assert r.status == 200
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_benchmarks_service():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/benchmarks/tps", json={
                "model": "mock-model", "requests": 6, "concurrency": 3,
            }, headers=headers)
            assert r.status == 202
            run_id = (await r.json())["run_id"]
            for _ in range(100):
                r = await gw.client.get(
                    f"/api/benchmarks/tps/{run_id}", headers=headers)
                data = await r.json()
                if data["status"] == "completed":
                    break
                await asyncio.sleep(0.05)
            assert data["status"] == "completed"
            assert data["succeeded"] == 6
            assert data["latency_ms"]["p50"] > 0
            assert data["per_endpoint"]
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())
