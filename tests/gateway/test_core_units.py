"""Units: token accounting, model names, sync parsing, registry, db roundtrip."""

import json

import pytest

from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.model_names import strip_quant_suffix, to_canonical, to_engine_name
from llmlb_tpu.gateway.model_sync import detect_capabilities, parse_models_response
from llmlb_tpu.gateway.registry import EndpointRegistry
from llmlb_tpu.gateway.token_accounting import (
    StreamingTokenAccumulator,
    estimate_tokens,
    extract_usage_from_response,
)
from llmlb_tpu.gateway.types import (
    Capability,
    Endpoint,
    EndpointModel,
    EndpointStatus,
    EndpointType,
)


def sse(payload: dict) -> bytes:
    return b"data: " + json.dumps(payload).encode() + b"\n\n"


def test_accumulator_captures_reported_usage():
    acc = StreamingTokenAccumulator()
    acc.feed(sse({"choices": [{"delta": {"content": "hel"}}]}))
    acc.feed(sse({"choices": [{"delta": {"content": "lo"}}]}))
    acc.feed(sse({"choices": [], "usage": {"prompt_tokens": 7, "completion_tokens": 2}}))
    acc.feed(b"data: [DONE]\n\n")
    pt, ct, reported = acc.finalize("hello prompt")
    assert (pt, ct, reported) == (7, 2, True)


def test_accumulator_estimates_when_no_usage():
    acc = StreamingTokenAccumulator()
    acc.feed(sse({"choices": [{"delta": {"content": "hello world, this is content"}}]}))
    pt, ct, reported = acc.finalize("some prompt text")
    assert not reported
    assert ct >= 1 and pt >= 1


def test_accumulator_handles_split_chunks():
    """SSE frames split mid-line across TCP reads must still parse."""
    acc = StreamingTokenAccumulator()
    frame = sse({"choices": [{"delta": {"content": "abc"}}],
                 "usage": {"prompt_tokens": 3, "completion_tokens": 1}})
    acc.feed(frame[:10])
    acc.feed(frame[10:])
    pt, ct, reported = acc.finalize()
    assert (pt, ct, reported) == (3, 1, True)


def test_extract_usage_variants():
    assert extract_usage_from_response(
        {"usage": {"prompt_tokens": 1, "completion_tokens": 2}}) == (1, 2)
    assert extract_usage_from_response(
        {"usage": {"input_tokens": 3, "output_tokens": 4}}) == (3, 4)
    assert extract_usage_from_response({}) is None


def test_estimate_tokens_nonzero():
    assert estimate_tokens("hello world this is a test") > 3
    assert estimate_tokens("") == 0


def test_model_name_mapping():
    assert to_canonical("llama3:8b") == "meta-llama/Meta-Llama-3-8B-Instruct"
    assert to_canonical("qwen2.5:0.5b") == "Qwen/Qwen2.5-0.5B-Instruct"
    assert to_canonical("unknown-model") == "unknown-model"
    assert to_engine_name("meta-llama/Meta-Llama-3-8B-Instruct", "ollama") == "llama3:8b"
    assert to_engine_name("meta-llama/Meta-Llama-3-8B-Instruct", "tpu") == "llama-3-8b"
    assert to_engine_name("whatever", "ollama") == "whatever"
    assert strip_quant_suffix("model-7b-Q4_K_M") == "model-7b"
    assert strip_quant_suffix("model.fp16") == "model"


def test_sync_parsing_both_shapes():
    openai_shape = {"data": [{"id": "m1"}, {"id": "m2", "max_model_len": 8192}]}
    assert [m["id"] for m in parse_models_response(openai_shape)] == ["m1", "m2"]
    ollama_shape = {"models": [{"name": "llama3:8b"}, {"model": "qwen2.5:0.5b"}]}
    assert [m["id"] for m in parse_models_response(ollama_shape)] == [
        "llama3:8b", "qwen2.5:0.5b"]
    assert parse_models_response({}) == []


def test_capability_heuristics():
    assert detect_capabilities("nomic-embed-text") == [Capability.EMBEDDINGS]
    assert detect_capabilities("whisper-large-v3") == [Capability.AUDIO_TRANSCRIPTION]
    assert detect_capabilities("sdxl") == [Capability.IMAGE_GENERATION]
    assert detect_capabilities("llama3:8b") == [Capability.CHAT_COMPLETION]


def test_explicit_capabilities_override_heuristics():
    """The tpu:// engine advertises capabilities in /v1/models entries
    (engine/server.py list_models); sync must honor them over name guesses."""
    from llmlb_tpu.gateway.model_sync import capabilities_from_meta

    meta = {"capabilities": ["chat_completion", "embeddings", "bogus"]}
    assert capabilities_from_meta(meta) == [
        Capability.CHAT_COMPLETION, Capability.EMBEDDINGS]
    assert capabilities_from_meta({}) is None
    assert capabilities_from_meta({"capabilities": ["nonsense"]}) is None


def test_registry_roundtrip_and_find(tmp_path):
    db = Database(str(tmp_path / "t.db"))
    reg = EndpointRegistry(db)
    ep = Endpoint(name="tpu0", base_url="http://127.0.0.1:8100",
                  endpoint_type=EndpointType.TPU)
    reg.add(ep)
    with pytest.raises(ValueError):
        reg.add(Endpoint(name="dup", base_url="http://127.0.0.1:8100/"))

    reg.update_status(ep.id, EndpointStatus.ONLINE, latency_ms=3.5)
    reg.sync_models(ep.id, [
        EndpointModel(endpoint_id=ep.id, model_id="llama-3-8b",
                      canonical_name="meta-llama/Meta-Llama-3-8B-Instruct"),
    ])
    found = reg.find_by_model("meta-llama/Meta-Llama-3-8B-Instruct")
    assert len(found) == 1 and found[0][0].id == ep.id
    # engine-local name also resolves
    assert len(reg.find_by_model("llama-3-8b")) == 1

    # persistence: a fresh registry over the same DB sees everything
    reg2 = EndpointRegistry(db)
    assert reg2.get(ep.id).status == EndpointStatus.ONLINE
    assert len(reg2.models_for(ep.id)) == 1

    assert reg.remove(ep.id)
    assert reg.find_by_model("llama-3-8b") == []


def test_registry_capability_listing(tmp_path):
    db = Database(str(tmp_path / "t.db"))
    reg = EndpointRegistry(db)
    ep = Endpoint(name="audio", base_url="http://127.0.0.1:9")
    reg.add(ep)
    reg.update_status(ep.id, EndpointStatus.ONLINE)
    reg.sync_models(ep.id, [
        EndpointModel(endpoint_id=ep.id, model_id="whisper-large-v3",
                      canonical_name="openai/whisper-large-v3",
                      capabilities=[Capability.AUDIO_TRANSCRIPTION]),
    ])
    assert len(reg.list_online_by_capability(Capability.AUDIO_TRANSCRIPTION)) == 1
    assert reg.list_online_by_capability(Capability.IMAGE_GENERATION) == []


def test_engine_tag_parsing():
    from llmlb_tpu.gateway.model_names import parse_engine_tag

    p = parse_engine_tag("llama3.1:8b-instruct-q4_K_M")
    assert p["family"] == "llama3.1"
    assert p["size"] == "8b"
    assert p["variant"] == "instruct"
    assert p["quant"] == "q4_k_m"

    p = parse_engine_tag("Meta-Llama-3-8B-Instruct.Q5_K_S.gguf")
    assert p["quant"] == "q5_k_s"

    p = parse_engine_tag("mistral:7b")
    assert p["size"] == "7b" and p["variant"] is None


def test_hf_repo_guessing():
    from llmlb_tpu.gateway.model_names import guess_hf_repo

    # table hits resolve exactly
    assert guess_hf_repo("llama3:8b") == "meta-llama/Meta-Llama-3-8B-Instruct"
    assert guess_hf_repo("mixtral:8x7b") == (
        "mistralai/Mixtral-8x7B-Instruct-v0.1"
    )
    # unknown names fall to family->org heuristics
    assert guess_hf_repo("qwen3:32b").startswith("Qwen/")
    assert guess_hf_repo("gemma3:4b").startswith("google/")
    assert guess_hf_repo("total-mystery-model") is None


def test_quant_alias_resolution():
    from llmlb_tpu.gateway.model_names import to_canonical

    assert to_canonical("llama3:8b") == "meta-llama/Meta-Llama-3-8B-Instruct"
    assert to_canonical("tinyllama:1.1b") == "TinyLlama/TinyLlama-1.1B-Chat-v1.0"
    assert to_canonical("bge-m3") == "BAAI/bge-m3"


def test_context_length_extraction():
    from llmlb_tpu.gateway.engine_metadata import _context_length_from

    assert _context_length_from(
        {"model_info": {"llama.context_length": 8192}}) == 8192
    assert _context_length_from({"max_context_length": "4096"}) == 4096
    assert _context_length_from({"details": {"num_ctx": 2048}}) == 2048
    assert _context_length_from({"nothing": 1}) is None
    assert _context_length_from({"context_length": -5}) is None
