"""Cookie sessions, CSRF double-submit protection, and the log-tail API.

Parity targets: auth/middleware.rs:431-479 (csrf_protect_middleware),
logging.rs:41-182 (rotating file sink), api/logs.rs:52 (log tail).
"""

import asyncio

from llmlb_tpu.gateway.auth import CSRF_COOKIE, JWT_COOKIE
from tests.support import ADMIN_PASSWORD, GatewayHarness


def _session_cookies(resp) -> dict:
    jar = {}
    for c in resp.headers.getall("Set-Cookie", []):
        first = c.split(";", 1)[0]
        k, _, v = first.partition("=")
        jar[k] = v
    return jar


async def _login_cookies(gw) -> dict:
    resp = await gw.client.post("/api/auth/login", json={
        "username": "admin", "password": ADMIN_PASSWORD,
    })
    assert resp.status == 200
    jar = _session_cookies(resp)
    assert JWT_COOKIE in jar and CSRF_COOKIE in jar
    return jar


def _cookie_header(jar: dict) -> str:
    return "; ".join(f"{k}={v}" for k, v in jar.items())


def test_cookie_session_get_works_without_csrf():
    async def run():
        gw = await GatewayHarness.create()
        try:
            jar = await _login_cookies(gw)
            resp = await gw.client.get(
                "/api/endpoints", headers={"Cookie": _cookie_header(jar)}
            )
            assert resp.status == 200
        finally:
            await gw.close()

    asyncio.run(run())


def test_cookie_post_requires_csrf_token():
    async def run():
        gw = await GatewayHarness.create()
        try:
            jar = await _login_cookies(gw)
            base = {"Cookie": _cookie_header(jar)}
            body = {"base_url": "http://127.0.0.1:9", "name": "x",
                    "endpoint_type": "openai_compatible"}

            # no CSRF header → 403
            resp = await gw.client.post("/api/endpoints", json=body,
                                        headers=base)
            assert resp.status == 403

            # wrong token → 403
            resp = await gw.client.post(
                "/api/endpoints", json=body,
                headers={**base, "x-csrf-token": "wrong"},
            )
            assert resp.status == 403

            # right token but cross-site origin → 403
            resp = await gw.client.post(
                "/api/endpoints", json=body,
                headers={**base, "x-csrf-token": jar[CSRF_COOKIE],
                         "Origin": "http://evil.example"},
            )
            assert resp.status == 403

            # right token + same origin → accepted
            host = f"http://{gw.client.host}:{gw.client.port}"
            resp = await gw.client.post(
                "/api/endpoints", json=body,
                headers={**base, "x-csrf-token": jar[CSRF_COOKIE],
                         "Origin": host},
            )
            assert resp.status == 201, await resp.text()
        finally:
            await gw.close()

    asyncio.run(run())


def test_bearer_auth_bypasses_csrf():
    """Header-authenticated requests are not CSRF targets."""

    async def run():
        gw = await GatewayHarness.create()
        try:
            resp = await gw.client.post(
                "/api/invitations", json={"role": "viewer"},
                headers=await gw.admin_headers(),
            )
            assert resp.status in (200, 201), await resp.text()
        finally:
            await gw.close()

    asyncio.run(run())


def test_cookie_csrf_missing_cookie_but_header_present():
    async def run():
        gw = await GatewayHarness.create()
        try:
            jar = await _login_cookies(gw)
            only_jwt = {JWT_COOKIE: jar[JWT_COOKIE]}
            resp = await gw.client.post(
                "/api/invitations", json={"role": "viewer"},
                headers={"Cookie": _cookie_header(only_jwt),
                         "x-csrf-token": jar[CSRF_COOKIE]},
            )
            assert resp.status == 403
        finally:
            await gw.close()

    asyncio.run(run())


def test_logout_clears_cookies():
    async def run():
        gw = await GatewayHarness.create()
        try:
            jar = await _login_cookies(gw)
            host = f"http://{gw.client.host}:{gw.client.port}"
            resp = await gw.client.post(
                "/api/auth/logout",
                headers={"Cookie": _cookie_header(jar),
                         "x-csrf-token": jar[CSRF_COOKIE], "Origin": host},
            )
            assert resp.status == 200
            cleared = _session_cookies(resp)
            assert cleared.get(JWT_COOKIE, "x") in ("", '""')
        finally:
            await gw.close()

    asyncio.run(run())


def test_rotating_log_sink_and_tail(tmp_path):
    from llmlb_tpu.gateway import logging_setup

    path = logging_setup.init_logging(str(tmp_path), file_sink=True)
    assert path is not None
    import logging as pylog

    for i in range(50):
        pylog.getLogger("llmlb_tpu.test").info("line %d", i)
    for h in pylog.getLogger().handlers:
        h.flush()
    lines = logging_setup.tail_log(10)
    assert len(lines) == 10
    assert "line 49" in lines[-1]
    # bounded even for absurd requests
    assert len(logging_setup.tail_log(10**9)) <= 5000
    logging_setup.init_logging(str(tmp_path), file_sink=False)


def test_log_tail_api():
    async def run():
        gw = await GatewayHarness.create()
        try:
            resp = await gw.client.get(
                "/api/dashboard/logs/lb", headers=await gw.admin_headers()
            )
            assert resp.status == 200
            body = await resp.json()
            assert "lines" in body and "available" in body
        finally:
            await gw.close()

    asyncio.run(run())


def test_cookie_jwt_rejected_on_v1_surface():
    """The dashboard cookie must never authenticate inference — a cross-site
    form POST rides cookies, and /v1/* has no CSRF middleware."""

    async def run():
        gw = await GatewayHarness.create()
        try:
            jar = await _login_cookies(gw)
            resp = await gw.client.post(
                "/v1/chat/completions",
                json={"model": "m", "messages": []},
                headers={"Cookie": _cookie_header(jar)},
            )
            assert resp.status == 401
        finally:
            await gw.close()

    asyncio.run(run())
