"""Dashboard SPA: served shell + assets + the data APIs it consumes.

Parity target: the reference embeds a built React bundle at /dashboard
(api/mod.rs:56,610-613); ours is a framework-light bundle committed under
gateway/dashboard_static/. No JS runtime exists in CI, so the contract is
tested at the HTTP layer: every asset the shell references serves, every
API call the views make returns the shape the views read, and the SPA
fallback route works.
"""

import asyncio
import os
import re

from tests.support import GatewayHarness, MockOpenAIEndpoint

STATIC_DIR = os.path.join(
    os.path.dirname(__file__), "..", "..",
    "llmlb_tpu", "gateway", "dashboard_static",
)


def _strip_js(src: str) -> str:
    """Drop comment/string/template/regex contents, keep structure chars.
    Handles nested template literals (mode stack) and the standard
    regex-vs-division heuristic (a '/' after (,=:[!&|?{}; starts a regex)."""
    out: list[str] = []
    stack: list[str] = []
    i, n = 0, len(src)
    mode = "code"
    last_sig = ""
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode == "code":
            if c == "/" and nxt == "/":
                while i < n and src[i] != "\n":
                    i += 1
                continue
            if c == "/" and nxt == "*":
                j = src.find("*/", i + 2)
                i = n if j < 0 else j + 2
                continue
            if c == "/" and last_sig in "(,=:[!&|?{};\n" or (
                c == "/" and last_sig == ""
            ):
                i += 1
                in_class = False
                while i < n and (src[i] != "/" or in_class):
                    if src[i] == "\\":
                        i += 2
                        continue
                    if src[i] == "[":
                        in_class = True
                    elif src[i] == "]":
                        in_class = False
                    i += 1
                i += 1
                while i < n and src[i].isalpha():  # flags
                    i += 1
                last_sig = "r"
                continue
            if c in "\"'":
                q = c
                i += 1
                while i < n and src[i] != q:
                    i += 2 if src[i] == "\\" else 1
                i += 1
                last_sig = "s"
                continue
            if c == "`":
                stack.append("tpl")
                mode = "tpl"
                i += 1
                continue
            if c == "}" and stack and stack[-1] == "interp":
                stack.pop()
                mode = "tpl"
                i += 1
                continue
            out.append(c)
            if not c.isspace():
                last_sig = c
            i += 1
        else:  # inside a template literal
            if c == "\\":
                i += 2
                continue
            if c == "`":
                stack.pop()
                mode = "code" if (not stack or stack[-1] == "interp") else "tpl"
                last_sig = "s"
                i += 1
                continue
            if c == "$" and nxt == "{":
                stack.append("interp")
                mode = "code"
                last_sig = "("
                i += 2
                continue
            i += 1
    return "".join(out)


def test_static_bundle_is_complete_and_balanced():
    """Every file referenced by index.html exists; JS bracket structure
    balances (coarse syntax tripwire given no JS runtime in the image)."""
    index = open(os.path.join(STATIC_DIR, "index.html")).read()
    refs = re.findall(r'(?:src|href)="/dashboard/([\w.\-]+)"', index)
    assert "style.css" in refs and "app.js" in refs
    for name in refs:
        assert os.path.isfile(os.path.join(STATIC_DIR, name)), name
    pairs = {"(": ")", "[": "]", "{": "}"}
    for js in ("app.js", "views.js", "charts.js"):
        src = open(os.path.join(STATIC_DIR, js)).read()
        stripped = _strip_js(src)
        opens: list[str] = []
        for ch in stripped:
            if ch in pairs:
                opens.append(ch)
            elif ch in pairs.values():
                assert opens and pairs[opens[-1]] == ch, (
                    f"{js}: unmatched {ch!r}"
                )
                opens.pop()
        assert not opens, f"{js}: unclosed {opens}"
    # views.js must export every route app.js wires up
    app_src = open(os.path.join(STATIC_DIR, "app.js")).read()
    views_src = open(os.path.join(STATIC_DIR, "views.js")).read()
    routes = re.findall(r"^\s+(\w+): views\.(\w+),", app_src, flags=re.M)
    for _, fn in routes:
        assert re.search(rf"export (?:async )?function {fn}\b", views_src), fn


def test_dashboard_serves_shell_and_assets():
    async def run():
        gw = await GatewayHarness.create()
        try:
            resp = await gw.client.get("/dashboard")
            assert resp.status == 200
            body = await resp.text()
            assert "llmlb" in body and "app.js" in body
            for asset in ("style.css", "app.js", "views.js", "charts.js"):
                r = await gw.client.get(f"/dashboard/{asset}")
                assert r.status == 200, asset
            # SPA fallback: unknown client-side routes serve the shell
            r = await gw.client.get("/dashboard/some/client/route")
            assert r.status == 200
            assert "app.js" in await r.text()
            # path traversal stays inside the static dir
            r = await gw.client.get("/dashboard/..%2F..%2Fapp_state.py")
            text = await r.text()
            assert "aiohttp" not in text
        finally:
            await gw.close()

    asyncio.run(run())


def test_spa_data_contract_end_to_end():
    """Drive every API the views consume against a live gateway with a mock
    endpoint and real traffic, asserting the exact keys the JS reads."""

    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="demo").start()
        try:
            gw.register_mock(mock.url, ["demo"], name="mock-1")
            headers = await gw.inference_headers()
            for _ in range(3):
                r = await gw.client.post("/v1/chat/completions", json={
                    "model": "demo",
                    "messages": [{"role": "user", "content": "hi"}],
                }, headers=headers)
                assert r.status == 200

            admin = await gw.admin_headers()

            ov = await (await gw.client.get(
                "/api/dashboard/overview", headers=admin)).json()
            assert ov["endpoints"]["online"] == 1
            assert ov["requests"]["today"] >= 3
            assert {"prompt", "completion"} <= set(ov["tokens_today"])

            hist = await (await gw.client.get(
                "/api/dashboard/request-history", headers=admin)).json()
            assert sum(m["requests"] for m in hist["minutes"]) >= 3
            assert {"ts", "requests", "errors"} <= set(hist["minutes"][0])

            tps = await (await gw.client.get(
                "/api/dashboard/model-tps", headers=admin)).json()
            assert any(k.endswith(":demo:chat") for k in tps["tps"])

            recs = await (await gw.client.get(
                "/api/dashboard/requests?limit=10", headers=admin)).json()
            assert len(recs["records"]) >= 3
            rec0 = recs["records"][0]
            assert {"id", "ts", "model", "status_code", "duration_ms"} <= set(rec0)
            detail = await (await gw.client.get(
                f"/api/dashboard/requests/{rec0['id']}", headers=admin)).json()
            assert detail["id"] == rec0["id"]

            stats = await (await gw.client.get(
                "/api/dashboard/token-stats?days=30", headers=admin)).json()
            assert {"total", "daily", "by_model"} <= set(stats)

            eps = await (await gw.client.get(
                "/api/endpoints", headers=admin)).json()
            assert eps["endpoints"][0]["models"][0]["canonical_name"] == "demo"

            au = await (await gw.client.get(
                "/api/audit-log?limit=10", headers=admin)).json()
            assert "entries" in au

            sysinfo = await (await gw.client.get(
                "/api/system", headers=admin)).json()
            assert "version" in sysinfo

            # playground pinned-endpoint proxy (EndpointPlayground.tsx parity)
            ep_id = eps["endpoints"][0]["id"]
            pg = await gw.client.post(
                f"/api/endpoints/{ep_id}/chat/completions",
                json={"model": "demo",
                      "messages": [{"role": "user", "content": "ping"}]},
                headers=admin,
            )
            assert pg.status == 200
            body = await pg.json()
            assert body["choices"][0]["message"]["content"]
        finally:
            await mock.stop()
            await gw.close()

    asyncio.run(run())
