"""Role-aware gateway routing for disaggregated prefill/decode
(docs/disaggregation.md): role surfaces on /api/endpoints, /api/health and
/v1/models; the health probe re-reads role every cycle; prefill-heavy
requests steer to prefill-capable endpoints; a prefill-only selection
triggers the two-phase handoff (prefill there, adopt on a decode-capable
endpoint) with SamplingParams extras surviving the wire; and the fallback
self-adoption keeps requests servable with no decode pool online.
"""

import asyncio
import json

from llmlb_tpu.disagg.gateway import (
    decode_capable,
    endpoint_role,
    prefill_capable,
    role_filter,
)
from llmlb_tpu.gateway.types import (
    AcceleratorInfo,
    Capability,
    EndpointStatus,
    EndpointType,
)
from tests.support import GatewayHarness, MockDisaggEndpoint

# comfortably past the 256-token prefill-heavy threshold
LONG_PROMPT = "please summarize this document carefully. " * 200
SHORT_PROMPT = "hi there"


def _set_role(gw, ep, role):
    gw.state.registry.update_status(
        ep.id, EndpointStatus.ONLINE,
        accelerator=AcceleratorInfo(role=role, sampled_at=1.0),
    )


def _chat_caps(*roles):
    return [Capability.CHAT_COMPLETION] + [Capability(r) for r in roles]


async def _chat(gw, prompt, **extra):
    resp = await gw.client.post(
        "/v1/chat/completions",
        json={"model": "m", "messages": [
            {"role": "user", "content": prompt}], **extra},
        headers=await gw.inference_headers(),
    )
    assert resp.status == 200, await resp.text()
    return await resp.json()


# ---------------------------------------------------------------- unit layer


def test_role_helpers_and_filter():
    class _Ep:
        def __init__(self, role):
            self.accelerator = AcceleratorInfo(role=role)

    both, split = _Ep(None), _Ep("split")
    pre, dec = _Ep("prefill"), _Ep("decode")
    assert endpoint_role(both) == "both"
    assert prefill_capable(pre) and not decode_capable(pre)
    assert decode_capable(dec) and not prefill_capable(dec)
    assert prefill_capable(split) and decode_capable(split)
    eps = [both, split, pre, dec]
    assert role_filter(eps, prefill_heavy=True) == [both, split, pre]
    assert role_filter(eps, prefill_heavy=False) == [both, split, dec]
    # soft: an empty preference falls back to the input unchanged
    assert role_filter([pre], prefill_heavy=False) == [pre]
    assert role_filter([dec], prefill_heavy=True) == [dec]


def test_role_capability_fallback_without_probe_telemetry():
    """Multi-worker: only the elected primary probes /api/health, so sibling
    workers have no accelerator.role — the role derived from the SYNCED
    capability list (persisted in the shared DB) must carry routing."""
    from llmlb_tpu.gateway.types import EndpointModel

    class _Ep:
        accelerator = AcceleratorInfo()  # never probed

    def model(*roles):
        return EndpointModel(
            endpoint_id="e", model_id="m", canonical_name="m",
            capabilities=_chat_caps(*roles),
        )

    ep = _Ep()
    assert endpoint_role(ep, model("prefill")) == "prefill"
    assert endpoint_role(ep, model("decode")) == "decode"
    assert endpoint_role(ep, model("prefill", "decode")) == "both"
    assert endpoint_role(ep, model()) == "both"
    # a probed role beats the capability fallback
    probed = _Ep()
    probed.accelerator = AcceleratorInfo(role="split")
    assert endpoint_role(probed, model("prefill")) == "split"


def test_routing_steers_on_capabilities_alone():
    """Same steering as test_short_prompts_avoid_prefill_only_endpoints but
    with NO probe telemetry set — the non-primary-worker view."""
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        dec = await MockDisaggEndpoint(role="decode", model="m").start()
        try:
            gw.register_mock(pre.url, ["m"], name="pre",
                             capabilities=_chat_caps("prefill"))
            gw.register_mock(dec.url, ["m"], name="dec",
                             capabilities=_chat_caps("decode"))
            for _ in range(3):
                await _chat(gw, SHORT_PROMPT, max_tokens=8)
            assert len(dec.requests_seen) == 3
            assert pre.requests_seen == []
            # long prompt: capability-derived prefill role still triggers
            # the two-phase handoff
            await _chat(gw, LONG_PROMPT, max_tokens=8)
            assert len(pre.prefill_calls) == 1
            assert len(dec.adopt_calls) == 1
        finally:
            await pre.stop()
            await dec.stop()
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------------------- role surfaces


def test_role_surfaces_and_probe_rereads_role():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockDisaggEndpoint(role="prefill", model="m").start()
        try:
            ep = gw.register_mock(mock.url, ["m"],
                                  endpoint_type=EndpointType.TPU,
                                  capabilities=_chat_caps("prefill"))

            from llmlb_tpu.gateway.health import EndpointHealthChecker

            checker = EndpointHealthChecker(
                gw.state.registry, gw.state.load_manager, gw.state.db,
                gw.state.http,
            )
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert gw.state.registry.get(ep.id).accelerator.role == "prefill"

            # /api/endpoints and /api/health carry the probed role
            resp = await gw.client.get("/api/endpoints",
                                       headers=await gw.admin_headers())
            body = await resp.json()
            assert body["endpoints"][0]["role"] == "prefill"
            resp = await gw.client.get("/api/health")
            health = await resp.json()
            assert health["endpoints"][0]["role"] == "prefill"

            # /v1/models capability list carries the role entries
            resp = await gw.client.get("/v1/models",
                                       headers=await gw.inference_headers())
            models = await resp.json()
            caps = models["data"][0]["metadata"]["capabilities"]
            assert "prefill" in caps

            # an engine restarted under a NEW role re-routes within one
            # probe: the checker re-reads role on every cycle
            mock.role = "decode"
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert gw.state.registry.get(ep.id).accelerator.role == "decode"
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------------- routing + handoff


def test_short_prompts_avoid_prefill_only_endpoints():
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        dec = await MockDisaggEndpoint(role="decode", model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            ep_dec = gw.register_mock(dec.url, ["m"], name="dec",
                                      capabilities=_chat_caps("decode"))
            _set_role(gw, ep_pre, "prefill")
            _set_role(gw, ep_dec, "decode")
            for _ in range(4):
                await _chat(gw, SHORT_PROMPT, max_tokens=8)
            # every short request landed on the decode-capable endpoint;
            # the prefill-only endpoint saw no /v1/chat/completions at all
            assert len(dec.requests_seen) == 4
            assert pre.requests_seen == []
        finally:
            await pre.stop()
            await dec.stop()
            await gw.close()
    asyncio.run(run())


def test_prefill_heavy_requests_orchestrate_the_two_phase_handoff():
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        dec = await MockDisaggEndpoint(role="decode", model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            ep_dec = gw.register_mock(dec.url, ["m"], name="dec",
                                      capabilities=_chat_caps("decode"))
            _set_role(gw, ep_pre, "prefill")
            _set_role(gw, ep_dec, "decode")
            body = await _chat(gw, LONG_PROMPT, max_tokens=8,
                               priority="low")
            # phase 1 hit the prefill endpoint, phase 2 the decode endpoint,
            # and the client got the adopter's completion
            assert len(pre.prefill_calls) == 1
            assert len(dec.adopt_calls) == 1
            content = json.loads(
                body["choices"][0]["message"]["content"]
            )
            assert content["adopted_by"] == "decode"
            assert content["committed"] == [7]
            # SamplingParams extras survived the handoff wire
            assert content["priority"] == 2
            assert gw.state.metrics.summary()["handoffs_total"] == 1
        finally:
            await pre.stop()
            await dec.stop()
            await gw.close()
    asyncio.run(run())


def test_handoff_streaming_relays_the_adopters_sse():
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        dec = await MockDisaggEndpoint(role="decode", model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            ep_dec = gw.register_mock(dec.url, ["m"], name="dec",
                                      capabilities=_chat_caps("decode"))
            _set_role(gw, ep_pre, "prefill")
            _set_role(gw, ep_dec, "decode")
            resp = await gw.client.post(
                "/v1/chat/completions",
                json={"model": "m", "stream": True, "max_tokens": 8,
                      "messages": [{"role": "user", "content": LONG_PROMPT}]},
                headers=await gw.inference_headers(),
            )
            assert resp.status == 200
            assert "text/event-stream" in resp.headers.get("Content-Type", "")
            text = ""
            async for line in resp.content:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    chunk = json.loads(line[6:])
                    for ch in chunk.get("choices", []):
                        text += (ch.get("delta") or {}).get("content") or ""
            assert json.loads(text)["adopted_by"] == "decode"
            assert dec.adopt_calls[0]["stream"] is True
        finally:
            await pre.stop()
            await dec.stop()
            await gw.close()
    asyncio.run(run())


def test_deadline_rides_the_adopt_request_as_remaining_budget():
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        dec = await MockDisaggEndpoint(role="decode", model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            ep_dec = gw.register_mock(dec.url, ["m"], name="dec",
                                      capabilities=_chat_caps("decode"))
            _set_role(gw, ep_pre, "prefill")
            _set_role(gw, ep_dec, "decode")
            resp = await gw.client.post(
                "/v1/chat/completions",
                json={"model": "m", "max_tokens": 8,
                      "messages": [{"role": "user", "content": LONG_PROMPT}]},
                headers={**await gw.inference_headers(),
                         "X-Request-Deadline-Ms": "30000"},
            )
            assert resp.status == 200
            # the wire payload carries the deadline as the prefill engine
            # received it (already decremented by gateway queue time); the
            # adopt request's header carries what remains AFTER prefill —
            # monotonically shrinking, never absent
            payload = dec.adopt_calls[0]["handoff"]
            wire_deadline = payload["sampling"]["deadline_ms"]
            assert 0 < wire_deadline <= 30000.0
            remaining = float(
                dec.adopt_headers[0]["X-Request-Deadline-Ms"]
            )
            assert 0 < remaining <= wire_deadline
        finally:
            await pre.stop()
            await dec.stop()
            await gw.close()
    asyncio.run(run())


def test_generic_endpoints_never_receive_the_handoff_wire():
    """Mixed fleet: a generic OpenAI-compatible endpoint defaults to role
    "both" for STEERING, but it has no /v1/handoff route — adoption must
    require an EXPLICIT decode advertisement, so the payload goes back to
    the originating engine (self-adoption), never at the generic box."""
    from llmlb_tpu.disagg.gateway import speaks_handoff_wire
    from llmlb_tpu.gateway.types import EndpointModel
    from tests.support import MockOpenAIEndpoint

    class _Ep:
        accelerator = AcceleratorInfo()

    plain_model = EndpointModel(endpoint_id="e", model_id="m",
                                canonical_name="m")
    assert decode_capable(_Ep(), plain_model)  # steering default...
    assert not speaks_handoff_wire(_Ep(), plain_model)  # ...but no wire

    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        plain = await MockOpenAIEndpoint(model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            gw.register_mock(plain.url, ["m"], name="plain")
            _set_role(gw, ep_pre, "prefill")
            body = await _chat(gw, LONG_PROMPT, max_tokens=8)
            # the prefill engine adopted its own payload; the generic
            # endpoint saw neither a handoff nor a 404
            assert len(pre.prefill_calls) == 1
            assert len(pre.adopt_calls) == 1
            content = json.loads(body["choices"][0]["message"]["content"])
            assert content["adopted_by"] == "prefill"
        finally:
            await pre.stop()
            await plain.stop()
            await gw.close()
    asyncio.run(run())


def test_no_adopter_falls_back_to_self_adoption():
    async def run():
        gw = await GatewayHarness.create()
        pre = await MockDisaggEndpoint(role="prefill", model="m").start()
        try:
            ep_pre = gw.register_mock(pre.url, ["m"], name="pre",
                                      capabilities=_chat_caps("prefill"))
            _set_role(gw, ep_pre, "prefill")
            body = await _chat(gw, LONG_PROMPT, max_tokens=8)
            # no decode-capable endpoint online: the prefill endpoint
            # adopted its own payload instead of bouncing the request
            assert len(pre.prefill_calls) == 1
            assert len(pre.adopt_calls) == 1
            content = json.loads(body["choices"][0]["message"]["content"])
            assert content["adopted_by"] == "prefill"
        finally:
            await pre.stop()
            await gw.close()
    asyncio.run(run())
