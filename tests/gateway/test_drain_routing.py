"""Drain-aware gateway routing (docs/deployment.md rolling-restart runbook):
a draining engine advertises itself on /api/health, the pull checker flips
it out of selection within one probe interval, and a model whose endpoints
are ALL draining queues and 503s (with Retry-After derived from the drain
grace) — it never 404s and nothing strands. Tier-1, no real engines.
"""

import asyncio

from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.types import EndpointStatus, EndpointType
from tests.support import GatewayHarness, MockResumableEndpoint

CHAT = "/v1/chat/completions"


def _chat_body(stream=False):
    body = {"model": "m",
            "messages": [{"role": "user", "content": "ping"}]}
    if stream:
        body["stream"] = True
    return body


def _checker(gw) -> EndpointHealthChecker:
    return EndpointHealthChecker(
        gw.state.registry, gw.state.load_manager, gw.state.db,
        gw.state.http, events=gw.state.events,
        interval_s=3600.0, timeout_s=2.0,
    )


def test_probe_flips_draining_endpoint_out_of_selection():
    """One probe cycle is enough: traffic stops landing on the draining
    engine, resumes when a later probe sees it healthy again."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a = await MockResumableEndpoint(model="m").start()
            b = await MockResumableEndpoint(model="m").start()
            ep_a = gw.register_mock(a.url, ["m"],
                                    endpoint_type=EndpointType.TPU,
                                    name="eng-a")
            gw.register_mock(b.url, ["m"], endpoint_type=EndpointType.TPU,
                             name="eng-b")
            checker = _checker(gw)
            headers = await gw.inference_headers()

            a.draining = True
            a.drain_remaining_s = 25.0
            await checker.check_all()
            # still ONLINE (its models must not 404) but ejected from
            # selection
            ep = gw.state.registry.get(ep_a.id)
            assert ep.status == EndpointStatus.ONLINE
            assert ep.accelerator.draining is True

            a_before = len(a.requests_seen)
            for _ in range(6):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200, await r.text()
                await r.read()
            assert len(a.requests_seen) == a_before  # zero new traffic
            assert len(b.requests_seen) >= 6

            # drain over (engine restarted): next probe restores selection
            a.draining = False
            a.drain_remaining_s = 0.0
            await checker.check_all()
            assert gw.state.registry.get(ep_a.id).accelerator.draining is False
            for _ in range(4):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200
                await r.read()
            assert len(a.requests_seen) > a_before  # traffic returned
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_all_endpoints_draining_queue_then_503_never_404():
    """Every endpoint for the model draining = a capacity condition: the
    request queues, then 503s with Retry-After derived from the soonest
    drain completion. It must never 404 — the model is still registered."""
    async def run():
        gw = await GatewayHarness.create()
        a = None
        try:
            a = await MockResumableEndpoint(model="m").start()
            gw.register_mock(a.url, ["m"], endpoint_type=EndpointType.TPU,
                             name="only")
            gw.state.load_manager.queue_config = QueueConfig(
                queue_timeout_s=0.2)
            checker = _checker(gw)
            a.draining = True
            a.drain_remaining_s = 12.0
            await checker.check_all()

            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 503, await r.text()
            retry_after = int(r.headers["Retry-After"])
            # derived from the advertised drain remaining (ceil(12) = 12)
            assert retry_after == 12
            body = await r.json()
            assert body["error"]["type"] == "server_error"
        finally:
            if a is not None:
                await a.stop()
            await gw.close()
    asyncio.run(run())


def test_anthropic_dialect_sees_drain_503_with_retry_after():
    async def run():
        gw = await GatewayHarness.create()
        a = None
        try:
            a = await MockResumableEndpoint(model="m").start()
            gw.register_mock(a.url, ["m"], endpoint_type=EndpointType.TPU,
                             name="only")
            gw.state.load_manager.queue_config = QueueConfig(
                queue_timeout_s=0.2)
            a.draining = True
            a.drain_remaining_s = 7.0
            await _checker(gw).check_all()
            headers = await gw.inference_headers()
            r = await gw.client.post(
                "/v1/messages",
                json={"model": "m", "max_tokens": 8,
                      "messages": [{"role": "user", "content": "hi"}]},
                headers=headers,
            )
            assert r.status == 503
            assert int(r.headers["Retry-After"]) == 7
            body = await r.json()
            assert body["error"]["type"] == "overloaded_error"
        finally:
            if a is not None:
                await a.stop()
            await gw.close()
    asyncio.run(run())
