"""Cross-host gossip mesh: UDP/TCP transport, seq-LWW, fault injection,
partitions, and fleet-global rate limits.

The mesh tests run two real `GossipBus` instances bound to loopback UDP
ports (separate unix directories, so ONLY the mesh can carry messages
between them). The federation-semantics tests reuse the multi-worker
harness (`_worker_states` pattern): shared DB, sibling unix buses — plus
`GossipFaults` to drop/delay/partition the transport deterministically.
"""

import asyncio
import json
import socket
import time

import pytest

from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.gossip import (
    GossipBus,
    GossipFaultRule,
    GossipFaults,
    MeshConfig,
    UDP_MAX_BYTES,
    encode_message,
)
from llmlb_tpu.gateway.resilience import BreakerState
from llmlb_tpu.gateway.types import Endpoint, EndpointStatus
from llmlb_tpu.gateway.worker import WorkerInfo


def _endpoint(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1234",
                    status=EndpointStatus.ONLINE)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _wait_for(predicate, timeout_s: float, interval_s: float = 0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval_s)
    return predicate()


async def _worker_states(tmp_path, monkeypatch, n: int, *, gossip=True,
                         port=45716):
    monkeypatch.setenv("LLMLB_GOSSIP_DIR", str(tmp_path / "bus"))
    monkeypatch.setenv("LLMLB_GOSSIP", "1" if gossip else "0")
    db_path = str(tmp_path / "gw.db")
    config = ServerConfig(port=port, database_url=db_path)
    states = []
    for i in range(n):
        states.append(await build_app_state(
            config, db=Database(db_path), start_background=False,
            worker=WorkerInfo(index=i, count=n),
        ))
    return states


# ------------------------------------------------------------- mesh transport


async def _mesh_pair(tmp_path):
    """Two buses on DIFFERENT unix dirs joined only by loopback UDP."""
    pa, pb = _free_port(), _free_port()
    addr_a, addr_b = f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"
    bus_a = GossipBus(str(tmp_path / "host-a"), 0,
                      mesh=MeshConfig(bind=addr_a, advertise=addr_a,
                                      peers=(addr_b,)))
    bus_b = GossipBus(str(tmp_path / "host-b"), 0,
                      mesh=MeshConfig(bind=addr_b, advertise=addr_b,
                                      peers=(addr_a,)))
    await bus_a.start()
    await bus_b.start()
    return bus_a, bus_b


async def test_mesh_udp_delivery_and_origin_identity(tmp_path):
    """A message published on host A arrives on host B over UDP, carrying
    a host-qualified origin so two hosts' worker-0 clocks never collide."""
    bus_a, bus_b = await _mesh_pair(tmp_path)
    got = []
    try:
        bus_b.subscribe("tps", lambda d, m: got.append((d, m)))
        bus_a.publish("tps", {"eid": "e1", "model": "m", "kind": "chat",
                              "ema": 120.0, "samples": 3})
        assert await _wait_for(lambda: got, 2.0), "UDP datagram never arrived"
        data, meta = got[0]
        assert data["ema"] == 120.0
        assert meta["origin"] == bus_a.origin
        assert "#w0" in meta["origin"] and "127.0.0.1" in meta["origin"]
        assert bus_a.origin != bus_b.origin  # same index, different host
    finally:
        bus_a.close()
        bus_b.close()


async def test_mesh_tcp_fallback_for_oversize_payloads(tmp_path):
    """A heat map too big for one UDP datagram rides the TCP side of the
    mesh port instead of being silently truncated or dropped."""
    bus_a, bus_b = await _mesh_pair(tmp_path)
    got = []
    try:
        bus_b.subscribe("heat", lambda d, m: got.append(d))
        entries = {f"prefixhash-{i:05d}": float(i) for i in range(4000)}
        payload = {"model": "m", "entries": entries}
        assert len(json.dumps(payload).encode()) > UDP_MAX_BYTES
        bus_a.publish("heat", payload)
        assert await _wait_for(lambda: got, 3.0), "oversize payload lost"
        assert got[0]["entries"] == entries
    finally:
        bus_a.close()
        bus_b.close()


async def test_mesh_stats_expose_peer_figures(tmp_path):
    bus_a, bus_b = await _mesh_pair(tmp_path)
    try:
        stats = bus_a.stats()
        for key in ("sent_total", "received_total", "recv_rejected_total",
                    "fault_dropped_total", "mesh_peers",
                    "partition_suspected"):
            assert key in stats, key
        assert bus_a.mesh_peer_count() == 1
    finally:
        bus_a.close()
        bus_b.close()


# ----------------------------------------------------------- seq-LWW ordering


async def test_skewed_wall_clock_cannot_resurrect_breaker_state(
    tmp_path, monkeypatch
):
    """Regression for the wall-stamp LWW this PR removed: a replayed OPEN
    carrying a wall timestamp an HOUR in the future but an OLD sequence
    number must lose to the newer CLOSED transition. Under ts-LWW it
    would have re-ejected a healthy endpoint fleet-wide."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45716)
    try:
        ep = _endpoint("engine-skew")
        s0.registry.add(ep)
        assert await _wait_for(lambda: s1.registry.get(ep.id) is not None, 2.0)

        threshold = s0.resilience.config.breaker_failure_threshold
        for _ in range(threshold):
            s0.resilience.record_failure(ep.id, "connect_error")
        assert await _wait_for(
            lambda: s1.resilience.state_of(ep.id) == BreakerState.OPEN, 1.0)
        s0.resilience.note_probe(ep.id, True)
        s0.resilience.on_admit(ep.id)
        s0.resilience.record_success(ep.id)
        assert await _wait_for(
            lambda: s1.resilience.state_of(ep.id) == BreakerState.CLOSED, 1.0)

        # the attack: an old OPEN (seq=1, long since superseded) replayed
        # with a future wall stamp, injected straight into s1's receiver
        stale = encode_message(
            "breaker",
            {"eid": ep.id, "to": "open", "reason": "stale-replay",
             "remaining_s": 30.0},
            origin=s0.gossip.origin, seq=1, ts=time.time() + 3600.0,
        )
        s1.gossip._on_datagram(stale)
        await asyncio.sleep(0.05)
        assert s1.resilience.state_of(ep.id) == BreakerState.CLOSED, (
            "a stale-seq/future-ts replay resurrected an open breaker"
        )
        assert s1.resilience.allow(ep.id)
    finally:
        await s0.close()
        await s1.close()


# --------------------------------------------------------- fault injection


def test_gossip_faults_env_parsing(monkeypatch):
    monkeypatch.setenv("LLMLB_GOSSIP_FAULTS", json.dumps([
        {"kind": "drop", "message": "tps", "probability": 1.0},
        {"kind": "partition", "groups": [["w0"], ["w1"]]},
    ]))
    faults = GossipFaults.from_env()
    assert faults is not None
    drop, _delay = faults.decide("tps", "w0", "w1")
    assert drop
    monkeypatch.setenv("LLMLB_GOSSIP_FAULTS", "not json")
    with pytest.raises(ValueError):
        GossipFaults.from_env()
    monkeypatch.delenv("LLMLB_GOSSIP_FAULTS")
    assert GossipFaults.from_env() is None


async def test_partition_no_resurrection_and_heal(tmp_path, monkeypatch):
    """Satellite: partition the two workers mid-flight. The cut side keeps
    converging from its OWN in-band failures (degraded, correct); healing
    the partition must not resurrect pre-partition state — only the
    newest transition wins — and fresh transitions flow again."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45717)
    try:
        ep = _endpoint("engine-part")
        s0.registry.add(ep)
        assert await _wait_for(lambda: s1.registry.get(ep.id) is not None, 2.0)

        wall = GossipFaults([GossipFaultRule(
            kind="partition", groups=[["w0"], ["w1"]])])
        s0.gossip.faults = wall
        s1.gossip.faults = wall

        threshold = s0.resilience.config.breaker_failure_threshold
        for _ in range(threshold):
            s0.resilience.record_failure(ep.id, "connect_error")
        assert s0.resilience.state_of(ep.id) == BreakerState.OPEN
        await asyncio.sleep(0.1)
        # the OPEN never crossed the wall...
        assert s1.resilience.state_of(ep.id) == BreakerState.CLOSED
        assert s0.gossip.stats()["fault_dropped_total"] > 0
        # ...but the cut-off worker still converges on its own evidence
        for _ in range(threshold):
            s1.resilience.record_failure(ep.id, "connect_error")
        assert not s1.resilience.allow(ep.id)

        # heal; s0 recovers the endpoint — the newer CLOSED must propagate
        wall.clear()
        s0.resilience.note_probe(ep.id, True)
        s0.resilience.on_admit(ep.id)
        s0.resilience.record_success(ep.id)
        assert s0.resilience.state_of(ep.id) == BreakerState.CLOSED
        assert await _wait_for(
            lambda: s1.resilience.state_of(ep.id) == BreakerState.CLOSED, 1.0
        ), "post-heal transition did not propagate"

        # and the pre-partition OPEN (older seq) can never resurrect
        stale = encode_message(
            "breaker",
            {"eid": ep.id, "to": "open", "reason": "pre-partition",
             "remaining_s": 30.0},
            origin=s0.gossip.origin, seq=2, ts=time.time(),
        )
        s1.gossip._on_datagram(stale)
        await asyncio.sleep(0.05)
        assert s1.resilience.state_of(ep.id) == BreakerState.CLOSED
    finally:
        await s0.close()
        await s1.close()


async def test_gossip_drop_faults_count_and_degrade(tmp_path, monkeypatch):
    """kind=drop at probability 1.0 silently eats matching messages and
    counts them — the sibling simply never learns (advisory state)."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45718)
    try:
        ep = _endpoint("engine-drop")
        s0.registry.add(ep)
        assert await _wait_for(lambda: s1.registry.get(ep.id) is not None, 2.0)
        s0.gossip.faults = GossipFaults([GossipFaultRule(
            kind="drop", message="tps", probability=1.0)])
        from llmlb_tpu.gateway.types import TpsApiKind

        s0.load_manager.update_tps(ep.id, "m", TpsApiKind.CHAT, 99, 1.0)
        await asyncio.sleep(0.1)
        assert s1.load_manager.get_tps(ep.id, "m", TpsApiKind.CHAT) is None
        assert s0.gossip.stats()["fault_dropped_total"] >= 1
    finally:
        await s0.close()
        await s1.close()


# ------------------------------------------------------ global token buckets


async def test_global_ratelimit_admits_n_fleet_wide(tmp_path, monkeypatch):
    """Acceptance: with gossip on, a tenant limited to burst B is admitted
    ≈B across the whole fleet — not B×workers. Spends replicate as
    rl_spend deltas and debit the sibling's full-limit buckets."""
    monkeypatch.setenv("LLMLB_RATELIMIT_RPS", "0.01")  # negligible refill
    monkeypatch.setenv("LLMLB_RATELIMIT_BURST", "8")
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45719)
    try:
        for s in (s0, s1):
            snap = s.ratelimit.snapshot()
            assert snap["global"] is True
            assert snap["workers_divisor"] == 1
        # 4 admissions on worker 0 (full-limit bucket: all allowed)
        for _ in range(4):
            assert s0.ratelimit.acquire("tenant-a").allowed
        s0.ratelimit.flush_spends(force=True)
        assert await _wait_for(
            lambda: s1.ratelimit.snapshot()["remote_spends_applied"] >= 1,
            1.0,
        ), "rl_spend delta never reached the sibling"
        # worker 1 sees fleet-wide consumption: exactly 4 slots remain of
        # the 8-burst (old local-share behavior would have granted 8 more)
        admitted = 0
        while s1.ratelimit.acquire("tenant-a").allowed:
            admitted += 1
            assert admitted < 16, "sibling ignored replicated spends"
        assert admitted == 4
        verdict = s1.ratelimit.acquire("tenant-a")
        assert not verdict.allowed and verdict.retry_after_s > 0
    finally:
        await s0.close()
        await s1.close()


async def test_ratelimit_without_gossip_enforces_local_share(
    tmp_path, monkeypatch
):
    """Gossip disabled: the limiter degrades to the conservative per-worker
    share (burst/workers), never over-admitting fleet-wide."""
    monkeypatch.setenv("LLMLB_RATELIMIT_RPS", "0.01")
    monkeypatch.setenv("LLMLB_RATELIMIT_BURST", "8")
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, gossip=False,
                                  port=45720)
    try:
        snap = s0.ratelimit.snapshot()
        assert snap["global"] is False
        assert snap["workers_divisor"] == 2
        admitted = 0
        while s0.ratelimit.acquire("tenant-b").allowed:
            admitted += 1
            assert admitted < 16
        assert admitted == 4  # 8-burst split across 2 workers
        # the sibling holds its own 4-slot share: worst case fleet-wide
        # admission is exactly the configured burst
        admitted1 = 0
        while s1.ratelimit.acquire("tenant-b").allowed:
            admitted1 += 1
            assert admitted1 < 16
        assert admitted1 == 4
    finally:
        await s0.close()
        await s1.close()


async def test_rebalance_directive_rides_gossip(tmp_path, monkeypatch):
    """A migrate directive published on one worker marks eligible streams
    in the SIBLING's directory — the primary plans, every worker moves
    its own streams."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45721)
    try:
        handle = s1.streams.register("rid-1", "m", "ep-hot")
        assert handle is not None
        ver = s0.gossip.publish("migrate", {
            "eid": "ep-hot", "target": "ep-idle", "reason": "drain",
            "max_streams": 2, "directive_id": 7,
        })
        assert await _wait_for(lambda: handle.pending is not None, 1.0), (
            "gossiped directive never marked the sibling's stream"
        )
        assert handle.pending == ("ep-idle", "drain", 7)
        # replayed datagrams must not double-apply (per-origin seq dedupe):
        # claim, then re-inject the SAME directive — nothing re-marks
        assert s1.streams.claim(handle) == ("ep-idle", "drain", 7)
        raw = encode_message("migrate", {
            "eid": "ep-hot", "target": "ep-idle", "reason": "drain",
            "max_streams": 2, "directive_id": 7,
        }, origin=s0.gossip.origin, seq=ver[0])
        s1.gossip._on_datagram(raw)
        await asyncio.sleep(0.05)
        assert handle.pending is None
    finally:
        await s0.close()
        await s1.close()
