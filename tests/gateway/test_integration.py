"""Integration tests: health failover, detection, sync, TPS-driven balancing.

Mirrors the reference integration tier (endpoint_health_check_test.rs,
endpoint_auto_recovery_test.rs, endpoint_latency_routing_test.rs).
"""

import asyncio

from llmlb_tpu.gateway.detection import Unreachable, detect_endpoint_type
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.model_sync import sync_endpoint_models
from llmlb_tpu.gateway.types import EndpointStatus, EndpointType, TpsApiKind
from tests.support import GatewayHarness, MockOllamaEndpoint, MockOpenAIEndpoint


def _checker(gw, interval=3600.0) -> EndpointHealthChecker:
    return EndpointHealthChecker(
        gw.state.registry, gw.state.load_manager, gw.state.db,
        gw.state.http, gw.state.events, interval_s=interval, timeout_s=2.0,
    )


def test_detection_priority():
    async def run():
        gw = await GatewayHarness.create()
        openai_mock = await MockOpenAIEndpoint().start()
        ollama_mock = await MockOllamaEndpoint().start()
        try:
            t = await detect_endpoint_type(openai_mock.url, gw.state.http)
            assert t == EndpointType.OPENAI_COMPATIBLE
            t = await detect_endpoint_type(ollama_mock.url, gw.state.http)
            assert t == EndpointType.OLLAMA
            try:
                await detect_endpoint_type("http://127.0.0.1:1", gw.state.http)
                assert False, "expected Unreachable"
            except Unreachable:
                pass
        finally:
            await openai_mock.stop()
            await ollama_mock.stop()
            await gw.close()
    asyncio.run(run())


def test_health_two_strike_offline_and_recovery():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m1").start()
        try:
            ep = gw.register_mock(mock.url, ["m1"])
            checker = _checker(gw)

            # healthy check keeps it online + records latency
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert gw.state.registry.get(ep.id).status == EndpointStatus.ONLINE
            assert gw.state.registry.get(ep.id).latency_ms is not None

            # seed TPS, then kill the endpoint
            gw.state.load_manager.update_tps(
                ep.id, "m1", TpsApiKind.CHAT, 100, 1.0)
            port = mock.server.port
            await mock.stop()

            # strike 1: still online
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert gw.state.registry.get(ep.id).status == EndpointStatus.ONLINE
            # strike 2: offline + TPS cleared
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert gw.state.registry.get(ep.id).status == EndpointStatus.OFFLINE
            assert gw.state.load_manager.get_tps(
                ep.id, "m1", TpsApiKind.CHAT) is None

            # offline endpoints are not selectable
            assert gw.state.registry.find_by_model("m1") == []

            # recovery on same port: online again + models resynced
            mock2 = MockOpenAIEndpoint(model="m2")
            from aiohttp.test_utils import TestServer as TS
            from aiohttp import web
            app = web.Application()
            app.router.add_get("/v1/models", mock2._models)
            mock2.server = TS(app, port=port)
            await mock2.server.start_server()
            try:
                await checker.check_endpoint(gw.state.registry.get(ep.id))
                ep_after = gw.state.registry.get(ep.id)
                assert ep_after.status == EndpointStatus.ONLINE
                models = [m.model_id for m in gw.state.registry.models_for(ep.id)]
                assert models == ["m2"]
            finally:
                await mock2.server.close()
        finally:
            await gw.close()
    asyncio.run(run())


def test_pending_endpoint_fails_fast():
    async def run():
        gw = await GatewayHarness.create()
        try:
            from llmlb_tpu.gateway.types import Endpoint
            ep = Endpoint(name="dead", base_url="http://127.0.0.1:1")
            gw.state.registry.add(ep)  # status PENDING
            checker = _checker(gw)
            await checker.check_endpoint(ep)
            assert gw.state.registry.get(ep.id).status == EndpointStatus.OFFLINE
            # health row persisted
            rows = gw.state.db.list_health_checks(ep.id)
            assert len(rows) == 1 and not rows[0]["ok"]
        finally:
            await gw.close()
    asyncio.run(run())


def test_model_sync_ollama_shape():
    async def run():
        gw = await GatewayHarness.create()
        ollama = await MockOllamaEndpoint(models=["llama3:8b", "nomic-embed-text"]).start()
        try:
            from llmlb_tpu.gateway.types import Capability, Endpoint
            ep = Endpoint(name="ol", base_url=ollama.url,
                          endpoint_type=EndpointType.OLLAMA)
            gw.state.registry.add(ep)
            added, removed = await sync_endpoint_models(
                ep, gw.state.registry, gw.state.http)
            assert (added, removed) == (2, 0)
            models = gw.state.registry.models_for(ep.id)
            by_id = {m.model_id: m for m in models}
            # canonical mapping + capability heuristics applied
            assert by_id["llama3:8b"].canonical_name == \
                "meta-llama/Meta-Llama-3-8B-Instruct"
            assert by_id["nomic-embed-text"].capabilities == [
                Capability.EMBEDDINGS]
        finally:
            await ollama.stop()
            await gw.close()
    asyncio.run(run())


def test_model_sync_honors_advertised_capabilities():
    """A tpu:// engine advertises capabilities in /v1/models (engine/server.py);
    sync must store them instead of falling back to name heuristics."""
    async def run():
        from aiohttp import web
        from aiohttp.test_utils import TestServer

        from llmlb_tpu.gateway.types import Capability, Endpoint

        async def models(request):
            return web.json_response({"object": "list", "data": [{
                "id": "debug-tiny", "object": "model",
                "capabilities": ["chat_completion", "embeddings"],
            }]})

        app = web.Application()
        app.router.add_get("/v1/models", models)
        server = TestServer(app)
        await server.start_server()
        gw = await GatewayHarness.create()
        try:
            ep = Endpoint(name="tpu", base_url=str(server.make_url("")).rstrip("/"))
            gw.state.registry.add(ep)
            await sync_endpoint_models(ep, gw.state.registry, gw.state.http)
            (model,) = gw.state.registry.models_for(ep.id)
            # 'debug-tiny' name-heuristics would say CHAT_COMPLETION only
            assert set(model.capabilities) == {
                Capability.CHAT_COMPLETION, Capability.EMBEDDINGS}
        finally:
            await gw.close()
            await server.close()
    asyncio.run(run())


def test_tps_balancing_prefers_faster_endpoint():
    """Two endpoints; the faster one (higher measured TPS) wins after probing."""
    async def run():
        gw = await GatewayHarness.create()
        fast = await MockOpenAIEndpoint(tokens_per_reply=50).start()
        slow = await MockOpenAIEndpoint(tokens_per_reply=50,
                                        reply_delay_s=0.3).start()
        try:
            ep_fast = gw.register_mock(fast.url, ["m"], name="fast")
            ep_slow = gw.register_mock(slow.url, ["m"], name="slow")
            headers = await gw.inference_headers()

            # probe phase: both get traffic (unmeasured → round-robin)
            for _ in range(4):
                r = await gw.client.post("/v1/chat/completions", json={
                    "model": "m",
                    "messages": [{"role": "user", "content": "x"}],
                }, headers=headers)
                assert r.status == 200

            lm = gw.state.load_manager
            tps_fast = lm.get_tps(ep_fast.id, "m", TpsApiKind.CHAT)
            tps_slow = lm.get_tps(ep_slow.id, "m", TpsApiKind.CHAT)
            assert tps_fast is not None and tps_slow is not None
            assert tps_fast > tps_slow

            # steady state: all traffic goes to the fast endpoint
            seen_before = len(fast.requests_seen)
            for _ in range(3):
                await gw.client.post("/v1/chat/completions", json={
                    "model": "m",
                    "messages": [{"role": "user", "content": "x"}],
                }, headers=headers)
            assert len(fast.requests_seen) == seen_before + 3
        finally:
            await fast.stop()
            await slow.stop()
            await gw.close()
    asyncio.run(run())


def test_endpoint_registration_via_api_with_detection_and_sync():
    """POST /api/endpoints detects type, health-checks, and syncs models."""
    async def run():
        gw = await GatewayHarness.create()
        # give the harness a real health checker for registration-time checks
        gw.state.health_checker = _checker(gw)
        mock = await MockOpenAIEndpoint(model="real-model").start()
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/endpoints", json={
                "base_url": mock.url}, headers=headers)
            assert r.status == 201
            created = await r.json()
            assert created["status"] == "online"
            assert [m["model_id"] for m in created["models"]] == ["real-model"]

            # immediately usable for inference
            iheaders = await gw.inference_headers()
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "real-model",
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=iheaders)
            assert r.status == 200
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_ollama_sync_enriches_context_length():
    """Per-engine metadata (reference metadata/ollama.rs): models synced from
    an Ollama endpoint get their context length from /api/show."""

    async def run():
        gw = await GatewayHarness.create()
        ollama = await MockOllamaEndpoint(models=("llama3:8b",)).start()
        try:
            from llmlb_tpu.gateway.model_sync import sync_endpoint_models
            from llmlb_tpu.gateway.types import Endpoint, EndpointStatus

            ep = Endpoint(name="o", base_url=ollama.url,
                          endpoint_type=EndpointType.OLLAMA,
                          status=EndpointStatus.ONLINE)
            gw.state.registry.add(ep)
            await sync_endpoint_models(ep, gw.state.registry, gw.state.http)
            models = gw.state.registry.models_for(ep.id)
            assert models[0].context_length == 8192
            assert models[0].canonical_name == (
                "meta-llama/Meta-Llama-3-8B-Instruct"
            )
        finally:
            await ollama.stop()
            await gw.close()

    asyncio.run(run())
