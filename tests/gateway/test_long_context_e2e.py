"""Long-context e2e (VERDICT r1 item 4 done-criterion): a prompt 4x beyond the
engine's largest one-shot prefill bucket streams a completion through the
gateway's /v1/chat/completions SSE path."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestServer

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.gateway.health import EndpointHealthChecker
from tests.support import GatewayHarness


@pytest.fixture(scope="module")
def engine():
    # largest bucket 32; slot capacity leaves room for a 4x-bucket prompt
    eng = Engine.from_preset(
        "debug-tiny", model_id="tpu-long", num_slots=2, slot_capacity=256,
        prefill_buckets=(16, 32),
    )
    yield eng
    eng.shutdown()


def test_long_prompt_streams_through_gateway(engine):
    async def run():
        gw = await GatewayHarness.create()
        engine_server = TestServer(create_engine_app(engine, owns_engine=False))
        await engine_server.start_server()
        engine_url = f"http://127.0.0.1:{engine_server.port}"
        gw.state.health_checker = EndpointHealthChecker(
            gw.state.registry, gw.state.load_manager, gw.state.db,
            gw.state.http, gw.state.events, interval_s=3600, timeout_s=5.0,
        )
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/endpoints", json={
                "base_url": engine_url, "name": "tpu-long"}, headers=headers)
            assert r.status == 201, await r.text()
            created = await r.json()
            assert created["status"] == "online", created
            assert [m["model_id"] for m in created["models"]] == ["tpu-long"], created

            iheaders = await gw.inference_headers()
            # ~135 chars -> >=130 byte-tokenizer tokens: 4x the 32 bucket
            long_prompt = "long context serving " * 7
            assert len(long_prompt) >= 4 * 32
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-long", "max_tokens": 5, "temperature": 0,
                "stream": True,
                "messages": [{"role": "user", "content": long_prompt}],
            }, headers=iheaders, timeout=120)
            assert r.status == 200, await r.text()
            raw = (await r.read()).decode()
            assert raw.strip().endswith("data: [DONE]")
            chunks = [
                json.loads(l[6:]) for l in raw.splitlines()
                if l.startswith("data: ") and l != "data: [DONE]"
            ]
            assert any(
                c["choices"] and c["choices"][0]["delta"].get("content")
                for c in chunks if c.get("choices")
            )
            usage = next(c["usage"] for c in reversed(chunks) if c.get("usage"))
            assert usage["prompt_tokens"] >= 4 * 32
            assert usage["completion_tokens"] >= 1
        finally:
            await engine_server.close()
            await gw.close()
    asyncio.run(run())
