"""Gateway-side multi-LoRA routing (llmlb_tpu/lora/gateway.py, docs/lora.md):
hot/load/refuse resolution, both-dialect 400 parity for the `lora` field,
adapter-aware prefix-affinity hashing, and the per-probe hot-adapter sync."""

import asyncio

import pytest

from llmlb_tpu.gateway.balancer import prefix_affinity_hash
from llmlb_tpu.gateway.types import Capability, EndpointType
from llmlb_tpu.lora.gateway import forward_model_name, lora_route_for
from tests.support import GatewayHarness, MockOpenAIEndpoint

CHAT = "/v1/chat/completions"
MESSAGES = "/v1/messages"
LONG_SYS = "You are a helpful assistant. " * 20  # clears the min-chars gate


# -------------------------------------------------------- affinity hashing


def test_affinity_hash_separates_adapters():
    """Regression (satellite): two adapters sharing a prompt must never
    share an affinity pin — under LoRA the warm KV they would steer to is
    adapter-specific."""
    h_base = prefix_affinity_hash("m", LONG_SYS)
    h_a = prefix_affinity_hash("m", LONG_SYS, lora="acme")
    h_b = prefix_affinity_hash("m", LONG_SYS, lora="globex")
    assert h_base and h_a and h_b
    assert len({h_base, h_a, h_b}) == 3
    # stability: the adapter-free key is unchanged vs the pre-LoRA hash
    assert h_base == prefix_affinity_hash("m", LONG_SYS, lora=None)
    assert h_a == prefix_affinity_hash("m", LONG_SYS, lora="acme")


# ---------------------------------------------------------- route resolution


def _register(gw, url, models, caps, name):
    return gw.register_mock(url, models, endpoint_type=EndpointType.TPU,
                            capabilities=caps, name=name)


def test_route_resolution_hot_load_refuse():
    async def run():
        gw = await GatewayHarness.create()
        try:
            lora_caps = [Capability.CHAT_COMPLETION, Capability.LORA]
            # hot: ep-a advertises the resident adapter as a model entry
            _register(gw, "http://h1", ["m", "m:acme"], lora_caps, "ep-a")
            # load-only: ep-b serves m with an adapter store, nothing hot
            _register(gw, "http://h2", ["m"], lora_caps, "ep-b")
            # capability-free endpoint: never a lora candidate
            _register(gw, "http://h3", ["m"],
                      [Capability.CHAT_COMPLETION], "ep-c")

            hot = lora_route_for(gw.state, {"model": "m:acme"})
            assert hot is not None and hot.kind == "hot"
            assert hot.canonical == "m:acme" and hot.adapter == "acme"

            load = lora_route_for(gw.state, {"model": "m", "lora": "cold"})
            assert load is not None and load.kind == "load"
            assert load.canonical == "m"
            assert load.capability is Capability.LORA

            # no lora-capable endpoint for the model at all → refuse,
            # naming the field
            with pytest.raises(ValueError, match="'lora'"):
                lora_route_for(gw.state, {"model": "other", "lora": "x"})

            # a literal colon-model that IS served routes normally
            _register(gw, "http://h4", ["llama3:8b"],
                      [Capability.CHAT_COMPLETION], "ep-d")
            assert lora_route_for(gw.state, {"model": "llama3:8b"}) is None

            # adapter-free request: no route object
            assert lora_route_for(gw.state, {"model": "m"}) is None
        finally:
            await gw.close()
    asyncio.run(run())


def test_forward_model_name():
    class R:
        adapter = "acme"
        kind = "hot"
    assert forward_model_name(R(), "eng-m:acme", "m") == "eng-m:acme"
    R.kind = "load"
    assert forward_model_name(R(), "eng-m", "m") == "eng-m:acme"
    assert forward_model_name(R(), None, "m") == "m:acme"
    assert forward_model_name(R(), "eng-m:acme", "m") == "eng-m:acme"


# ------------------------------------------------- both-dialect 400 parity


def test_lora_field_400_parity_both_dialects():
    """Malformed `lora` values and fleet-unserveable adapters 400 on BOTH
    dialects with the field named — the engine-server/gateway parity the
    speculative/response_format validators established."""
    async def run():
        gw = await GatewayHarness.create()
        mock = None
        try:
            mock = await MockOpenAIEndpoint(model="m").start()
            _register(gw, mock.url, ["m"],
                      [Capability.CHAT_COMPLETION, Capability.LORA], "ep")
            headers = await gw.inference_headers()
            msgs = [{"role": "user", "content": "hi"}]

            for bad, needle in (
                (7, "'lora'"),
                ("bad name", "'lora'"),
            ):
                r = await gw.client.post(CHAT, json={
                    "model": "m", "messages": msgs, "lora": bad,
                }, headers=headers)
                assert r.status == 400, await r.text()
                assert needle in (await r.json())["error"]["message"]

                r = await gw.client.post(MESSAGES, json={
                    "model": "m", "max_tokens": 8, "messages": msgs,
                    "lora": bad,
                }, headers=headers)
                assert r.status == 400, await r.text()
                body = await r.json()
                assert body["type"] == "error"
                assert needle in body["error"]["message"]

            # adapter for a model with no lora-capable endpoint: 400 naming
            # the field (before 404ing), both dialects
            r = await gw.client.post(CHAT, json={
                "model": "elsewhere", "messages": msgs, "lora": "acme",
            }, headers=headers)
            assert r.status == 400
            assert "'lora'" in (await r.json())["error"]["message"]
            r = await gw.client.post(MESSAGES, json={
                "model": "elsewhere", "max_tokens": 8, "messages": msgs,
                "lora": "acme",
            }, headers=headers)
            assert r.status == 400
            assert "'lora'" in (await r.json())["error"]["message"]

            summary = gw.state.metrics.summary()
            assert summary["lora_requests_total"] >= 6
        finally:
            if mock is not None:
                await mock.stop()
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------------- end-to-end forward


def test_adapter_forwarded_to_engine_both_dialects():
    """The selected engine sees the adapter on the model name AND the
    explicit field (cold-load route), on both dialects; the gateway's
    route counter records the load."""
    async def run():
        gw = await GatewayHarness.create()
        mock = None
        try:
            mock = await MockOpenAIEndpoint(model="m").start()
            _register(gw, mock.url, ["m"],
                      [Capability.CHAT_COMPLETION, Capability.LORA], "ep")
            headers = await gw.inference_headers()
            msgs = [{"role": "user", "content": "hi"}]

            r = await gw.client.post(CHAT, json={
                "model": "m:acme", "messages": msgs, "max_tokens": 4,
            }, headers=headers)
            assert r.status == 200, await r.text()
            seen = mock.requests_seen[-1]
            assert seen["model"] == "m:acme" and seen["lora"] == "acme"

            r = await gw.client.post(MESSAGES, json={
                "model": "m", "lora": "acme", "max_tokens": 4,
                "messages": msgs,
            }, headers=headers)
            assert r.status == 200, await r.text()
            seen = mock.requests_seen[-1]
            assert seen["model"] == "m:acme" and seen["lora"] == "acme"

            text = gw.state.metrics.render()
            assert 'llmlb_gateway_lora_requests_total{route="load"} 2' \
                in text
        finally:
            if mock is not None:
                await mock.stop()
            await gw.close()
    asyncio.run(run())


# --------------------------------------------- per-probe hot-adapter sync


def test_health_probe_mirrors_resident_adapters_into_models():
    """The health checker turns a probe's lora.resident advertisement into
    `base:adapter` model entries (and removes them when they evict), so
    hot-routing reacts within one probe interval — the disagg-role
    re-parse precedent."""
    from aiohttp import web
    from aiohttp.test_utils import TestServer

    resident = ["acme"]

    async def health(request):
        return web.json_response({
            "status": "ok",
            "tpu": {"accelerator": "tpu", "chip_count": 1},
            "engine": {"num_slots": 4, "active_slots": 0, "queued": 0},
            "lora": {"enabled": True, "resident": list(resident),
                     "available": ["acme", "coldone"]},
        })

    async def run():
        gw = await GatewayHarness.create()
        server = None
        try:
            app = web.Application()
            app.router.add_get("/api/health", health)
            server = TestServer(app)
            await server.start_server()
            url = f"http://127.0.0.1:{server.port}"
            ep = _register(gw, url, ["m"],
                           [Capability.CHAT_COMPLETION, Capability.LORA],
                           "ep")

            from llmlb_tpu.gateway.health import EndpointHealthChecker

            checker = EndpointHealthChecker(
                gw.state.registry, gw.state.load_manager, gw.state.db,
                session=gw.state.http,
            )
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            ids = {m.model_id for m in gw.state.registry.models_for(ep.id)}
            assert ids == {"m", "m:acme"}
            route = lora_route_for(gw.state, {"model": "m:acme"})
            assert route is not None and route.kind == "hot"

            # eviction: the adapter leaves the advertisement → entry drops
            resident.clear()
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            ids = {m.model_id for m in gw.state.registry.models_for(ep.id)}
            assert ids == {"m"}
            route = lora_route_for(gw.state, {"model": "m:acme"})
            assert route is not None and route.kind == "load"
            # a non-resident but STORE-AVAILABLE adapter cold-loads...
            route = lora_route_for(gw.state, {"model": "m:coldone"})
            assert route is not None and route.kind == "load"
            # ...but a name in NO advertised store refuses with a clean
            # 400 naming the field, instead of proxying to a certain
            # engine-side 400
            with pytest.raises(ValueError, match="'lora'"):
                lora_route_for(gw.state, {"model": "m", "lora": "ghost"})
        finally:
            if server is not None:
                await server.close()
            await gw.close()
    asyncio.run(run())
