"""End-to-end multimodal slice: a tpu:// engine hosting ASR/TTS/image services
registered into the gateway; the gateway's capability routing (api/audio.rs /
api/images.rs parity) must carry speech, transcription, and image requests
through to the in-tree engine."""

import asyncio
import base64

import pytest
from aiohttp.test_utils import TestServer

from llmlb_tpu.engine.asr import AsrEngine
from llmlb_tpu.engine.image import ImageEngine
from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.engine.tts import TtsEngine
from tests.support import GatewayHarness


@pytest.fixture(scope="module")
def engine():
    eng = Engine.from_preset(
        "debug-tiny", model_id="tpu-mm", num_slots=2, slot_capacity=64,
        prefill_buckets=(16, 32),
    )
    yield eng
    eng.shutdown()


def test_multimodal_capability_routing_through_gateway(engine):
    async def run():
        gw = await GatewayHarness.create()
        asr = AsrEngine.from_random(seed=1, model_id="whisper-test")
        tts = TtsEngine.from_random(seed=2, model_id="tts-test")
        image = ImageEngine.from_random(seed=3, model_id="diffusion-test",
                                        sample_steps=2)
        engine_server = TestServer(create_engine_app(
            engine, owns_engine=False, asr=asr, tts=tts, image=image))
        await engine_server.start_server()
        engine_url = f"http://127.0.0.1:{engine_server.port}"
        from llmlb_tpu.gateway.health import EndpointHealthChecker

        gw.state.health_checker = EndpointHealthChecker(
            gw.state.registry, gw.state.load_manager, gw.state.db,
            gw.state.http, gw.state.events, interval_s=3600, timeout_s=5.0,
        )
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/endpoints", json={
                "base_url": engine_url, "name": "tpu-mm"}, headers=headers)
            assert r.status == 201, await r.text()
            created = await r.json()
            # sync picked up all four models with advertised capabilities
            by_id = {m["model_id"]: m for m in created["models"]}
            assert set(by_id) == {
                "tpu-mm", "whisper-test", "tts-test", "diffusion-test"}
            assert by_id["tts-test"]["capabilities"] == ["audio_speech"]

            iheaders = await gw.inference_headers()

            # speech: gateway routes by AudioSpeech capability
            r = await gw.client.post("/v1/audio/speech", json={
                "model": "tts-test", "input": "route me", "voice": "alloy",
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            wav = await r.read()
            assert wav[:4] == b"RIFF"

            # transcription: multipart re-proxy (audio.rs:199-370 parity)
            import aiohttp
            form = aiohttp.FormData()
            form.add_field("file", wav, filename="x.wav",
                           content_type="audio/wav")
            form.add_field("model", "whisper-test")
            r = await gw.client.post("/v1/audio/transcriptions", data=form,
                                     headers=iheaders)
            assert r.status == 200, await r.text()
            assert "text" in await r.json()

            # images
            r = await gw.client.post("/v1/images/generations", json={
                "model": "diffusion-test", "prompt": "tiny", "n": 1,
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            body = await r.json()
            png = base64.b64decode(body["data"][0]["b64_json"])
            assert png[:8] == b"\x89PNG\r\n\x1a\n"

            # no capable endpoint -> 404 (capability filter works)
            r = await gw.client.post("/v1/audio/speech", json={
                "model": "no-such-model", "input": "x"}, headers=iheaders)
            assert r.status == 404
        finally:
            await engine_server.close()
            await gw.close()
    asyncio.run(run())
