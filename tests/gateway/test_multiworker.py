"""Multi-worker gateway: gossip replication, consistent-hash affinity,
SO_REUSEPORT serving.

Most tests model N workers in one process: N AppStates, each built with its
own SQLite connection to one shared WAL file and its own GossipBus socket in
one shared directory — exactly the state a forked worker holds, minus the
fork. The last test boots the real thing (``serve --workers 2``) and checks
the shared port + worker-labeled /metrics end to end.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.balancer import LoadManager, hrw_owner
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.resilience import BreakerState
from llmlb_tpu.gateway.types import Endpoint, EndpointStatus, TpsApiKind
from llmlb_tpu.gateway.worker import WorkerInfo

BREAKER_PROPAGATION_BUDGET_S = 0.25  # the acceptance bound


def _endpoint(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1234",
                    status=EndpointStatus.ONLINE)


async def _worker_states(tmp_path, monkeypatch, n: int, *, gossip=True,
                         port=45711):
    """N shared-nothing AppStates wired like forked workers: shared DB file,
    shared gossip dir, separate connections/buses."""
    monkeypatch.setenv("LLMLB_GOSSIP_DIR", str(tmp_path / "bus"))
    monkeypatch.setenv("LLMLB_GOSSIP", "1" if gossip else "0")
    db_path = str(tmp_path / "gw.db")
    config = ServerConfig(port=port, database_url=db_path)
    states = []
    for i in range(n):
        states.append(await build_app_state(
            config, db=Database(db_path), start_background=False,
            worker=WorkerInfo(index=i, count=n),
        ))
    return states


async def _wait_for(predicate, timeout_s: float, interval_s: float = 0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval_s)
    return predicate()


# ------------------------------------------------------------------- breaker


async def test_breaker_trip_propagates_across_workers(tmp_path, monkeypatch):
    """A breaker tripped on one worker ejects the endpoint on its sibling
    within the 250 ms acceptance budget (gossip, not the 30 s health
    probe)."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2)
    try:
        ep = _endpoint("engine-a")
        s0.registry.add(ep)
        # registry mutation gossips; the sibling reloads from the shared DB
        assert await _wait_for(lambda: s1.registry.get(ep.id) is not None, 2.0)

        threshold = s0.resilience.config.breaker_failure_threshold
        t0 = time.monotonic()
        for _ in range(threshold):
            s0.resilience.record_failure(ep.id, "connect_error")
        assert s0.resilience.state_of(ep.id) == BreakerState.OPEN
        assert not s0.resilience.allow(ep.id)

        assert await _wait_for(
            lambda: not s1.resilience.allow(ep.id),
            BREAKER_PROPAGATION_BUDGET_S,
        ), "breaker open did not propagate to the sibling worker in 250ms"
        propagation_s = time.monotonic() - t0
        assert s1.resilience.state_of(ep.id) == BreakerState.OPEN
        assert propagation_s < BREAKER_PROPAGATION_BUDGET_S

        # recovery propagates too: the tripping worker's probe success
        # closes the breaker everywhere
        s0.resilience.note_probe(ep.id, True)  # open -> half_open
        s0.resilience.on_admit(ep.id)
        s0.resilience.record_success(ep.id)  # half_open -> closed
        assert await _wait_for(
            lambda: s1.resilience.state_of(ep.id) == BreakerState.CLOSED, 1.0
        ), "breaker close did not propagate"
    finally:
        await s0.close()
        await s1.close()


async def test_gossip_disabled_workers_converge_independently(
    tmp_path, monkeypatch
):
    """LLMLB_GOSSIP=0: no replication, but correctness holds — each worker
    trips its own breaker from its own in-band failures."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, gossip=False)
    try:
        assert s0.gossip is None and s1.gossip is None
        ep = _endpoint("engine-b")
        s0.registry.add(ep)
        s1.registry.reload()  # no gossip: manual reload stands in for boot

        threshold = s0.resilience.config.breaker_failure_threshold
        for _ in range(threshold):
            s0.resilience.record_failure(ep.id, "connect_error")
        assert not s0.resilience.allow(ep.id)
        await asyncio.sleep(0.1)
        # sibling unaffected (nothing replicated)...
        assert s1.resilience.allow(ep.id)
        # ...and converges the moment its own failures arrive
        for _ in range(threshold):
            s1.resilience.record_failure(ep.id, "connect_error")
        assert not s1.resilience.allow(ep.id)
    finally:
        await s0.close()
        await s1.close()


# ----------------------------------------------------------- tps + affinity


async def test_tps_ema_gossips_between_workers(tmp_path, monkeypatch):
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2)
    try:
        ep = _endpoint("engine-c")
        s0.registry.add(ep)
        s0.load_manager.update_tps(ep.id, "m", TpsApiKind.CHAT, 120, 1.0)
        assert await _wait_for(
            lambda: s1.load_manager.get_tps(ep.id, "m", TpsApiKind.CHAT)
            is not None, 1.0,
        ), "TPS EMA did not replicate"
        got = s1.load_manager.get_tps(ep.id, "m", TpsApiKind.CHAT)
        assert got == pytest.approx(120.0)
    finally:
        await s0.close()
        await s1.close()


async def test_retry_budget_spend_gossips(tmp_path, monkeypatch):
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2)
    try:
        before = s1.resilience.budget.snapshot()["retries_in_window"]
        assert s0.resilience.budget.try_spend()
        assert await _wait_for(
            lambda: s1.resilience.budget.snapshot()["retries_in_window"]
            == before + 1, 1.0,
        ), "retry spend did not replicate into the sibling's window"
    finally:
        await s0.close()
        await s1.close()


async def test_ring_affinity_agrees_across_workers(tmp_path, monkeypatch):
    """Consistent-hash mode (the multi-worker default): every worker maps
    the same prompt head to the same endpoint with zero coordination."""
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2)
    try:
        assert s0.load_manager.affinity_mode == "ring"
        assert s1.load_manager.affinity_mode == "ring"
        endpoints = [_endpoint(f"engine-{i}") for i in range(4)]
        for h in (f"prefixhash-{k}" for k in range(32)):
            picks = set()
            for lm in (s0.load_manager, s1.load_manager):
                got = lm.select_endpoint(endpoints, "m", prefix_hash=h)
                picks.add(got.id)
            assert len(picks) == 1, f"workers disagreed on prefix {h}"
    finally:
        await s0.close()
        await s1.close()


def test_ring_remap_fraction_on_endpoint_removal():
    """Removing one of E endpoints remaps only the keys it owned (~1/E);
    every other key keeps its endpoint exactly — the consistent-hash
    property that keeps (E-1)/E of engine prefix caches warm through
    churn."""
    ids = [f"ep-{i}" for i in range(5)]
    keys = [f"prompthash-{k}" for k in range(1000)]
    before = {k: hrw_owner(k, ids) for k in keys}
    removed = "ep-2"
    survivors = [e for e in ids if e != removed]
    after = {k: hrw_owner(k, survivors) for k in keys}
    remapped = [k for k in keys if before[k] != after[k]]
    # only keys the removed endpoint owned may move...
    assert all(before[k] == removed for k in remapped)
    # ...and all of its keys must move (it is gone)
    owned = [k for k in keys if before[k] == removed]
    assert set(remapped) == set(owned)
    frac = len(remapped) / len(keys)
    assert 0.10 < frac < 0.32, f"remap fraction {frac} not ~1/5"


def test_ring_mode_single_manager_sticks_and_counts():
    """Ring mode through the LoadManager selection paths: deterministic
    stickiness, hit/miss accounting, at-cap fallback."""
    lm = LoadManager(use_native=False, affinity_mode="ring")
    endpoints = [_endpoint(f"e{i}") for i in range(3)]
    h = "deadbeef" * 5
    first = lm.select_endpoint(endpoints, "m", prefix_hash=h)
    for _ in range(5):
        assert lm.select_endpoint(endpoints, "m", prefix_hash=h) is first
    stats = lm.affinity_stats()
    assert stats["hits_total"] == 6
    assert stats["misses_total"] == 0
    assert stats["entries"] == 0  # ring mode stores nothing

    # owner saturated at cap: falls back to scoring, counts a miss
    from llmlb_tpu.gateway.config import QueueConfig

    lm2 = LoadManager(QueueConfig(max_active_per_endpoint=1),
                      use_native=False, affinity_mode="ring")
    got = lm2.try_admit(endpoints, "m", TpsApiKind.CHAT, prefix_hash=h)
    assert got is not None and got[0] is first
    got2 = lm2.try_admit(endpoints, "m", TpsApiKind.CHAT, prefix_hash=h)
    assert got2 is not None and got2[0] is not first
    assert lm2.affinity_stats()["misses_total"] == 1
    got[1].fail()
    got2[1].fail()
    # capacity freed: the key snaps back to its owner
    got3 = lm2.try_admit(endpoints, "m", TpsApiKind.CHAT, prefix_hash=h)
    assert got3 is not None and got3[0] is first
    got3[1].fail()


def test_ring_native_python_parity():
    try:
        from llmlb_tpu.native import native_hrw_available, native_hrw_select
    except ImportError:
        pytest.skip("native module unavailable")
    if not native_hrw_available():
        pytest.skip("native hrw_select unavailable (run `make -C native`)")
    ids = [f"endpoint-{i}" for i in range(7)]
    for k in range(300):
        key = f"prefix-{k:04d}"
        assert ids[native_hrw_select(key, ids)] == hrw_owner(key, ids)


async def test_lru_affinity_pin_gossips(tmp_path, monkeypatch):
    """LLMLB_AFFINITY=lru with multiple workers: learned pins replicate so
    siblings steer the same prefix without re-learning."""
    monkeypatch.setenv("LLMLB_AFFINITY", "lru")
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45713)
    try:
        assert s0.load_manager.affinity_mode == "lru"
        endpoints = [_endpoint(f"engine-{i}") for i in range(3)]
        h = "feedface" * 5
        pinned = s0.load_manager.select_endpoint(endpoints, "m",
                                                 prefix_hash=h)
        assert await _wait_for(
            lambda: s1.load_manager._affinity_endpoint("m", h) == pinned.id,
            1.0,
        ), "lru affinity pin did not replicate"
        assert s1.load_manager.select_endpoint(
            endpoints, "m", prefix_hash=h
        ) is pinned
    finally:
        await s0.close()
        await s1.close()


# ----------------------------------------------------------- registry + db


async def test_admin_mutation_reaches_sibling_registry(tmp_path, monkeypatch):
    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45714)
    try:
        ep = _endpoint("late-endpoint")
        s0.registry.add(ep)
        assert await _wait_for(
            lambda: s1.registry.get(ep.id) is not None, 2.0
        ), "endpoint added on one worker never appeared on the sibling"
        s0.registry.remove(ep.id)
        assert await _wait_for(
            lambda: s1.registry.get(ep.id) is None, 2.0
        ), "endpoint removal never propagated"
    finally:
        await s0.close()
        await s1.close()


async def test_audit_chain_survives_concurrent_worker_flushes(
    tmp_path, monkeypatch
):
    """Two workers flushing audit batches into one WAL file must keep the
    hash chain linear (the atomic BEGIN IMMEDIATE flush)."""
    from llmlb_tpu.gateway.audit import AuditEntry

    s0, s1 = await _worker_states(tmp_path, monkeypatch, 2, port=45715)
    try:
        for i in range(30):
            s = (s0, s1)[i % 2]
            s.audit.record(AuditEntry(
                ts=time.time(), method="GET", path=f"/x/{i}", status=200,
                duration_ms=1.0,
            ))
            if i % 5 == 4:
                s0.audit.flush()
                s1.audit.flush()
        s0.audit.flush()
        s1.audit.flush()
        ok, err = s0.audit.verify()
        assert ok, f"audit chain broken across workers: {err}"
    finally:
        await s0.close()
        await s1.close()


# -------------------------------------------------- satellites: knobs + logs


def test_uvloop_knob_graceful_fallback(monkeypatch):
    """LLMLB_UVLOOP=1 without uvloop installed must log-and-continue, not
    crash the server; =0 must not touch the loop policy at all."""
    from llmlb_tpu.gateway.server import maybe_install_uvloop

    monkeypatch.setenv("LLMLB_UVLOOP", "0")
    assert maybe_install_uvloop() is False
    monkeypatch.setenv("LLMLB_UVLOOP", "1")
    try:
        import uvloop  # noqa: F401

        has_uvloop = True
    except ImportError:
        has_uvloop = False
    policy_before = asyncio.get_event_loop_policy()
    try:
        assert maybe_install_uvloop() is has_uvloop
    finally:
        asyncio.set_event_loop_policy(policy_before)


def test_log_format_carries_worker_id(monkeypatch, tmp_path):
    import logging

    from llmlb_tpu.gateway.logging_setup import (
        DEFAULT_LOG_FORMAT,
        init_logging,
    )

    assert "%(worker)s" in DEFAULT_LOG_FORMAT  # the documented default
    monkeypatch.setenv("LLMLB_WORKER_INDEX", "3")
    monkeypatch.delenv("LLMLB_LOG_FORMAT", raising=False)
    init_logging(str(tmp_path), file_sink=False)
    try:
        record = logging.getLogger("llmlb_tpu.test").makeRecord(
            "llmlb_tpu.test", logging.INFO, __file__, 1, "hello", (), None
        )
        line = logging.Formatter(DEFAULT_LOG_FORMAT).format(record)
        assert " w3 " in line
        # custom format override wins
        monkeypatch.setenv("LLMLB_LOG_FORMAT", "%(levelname)s|%(message)s")
        init_logging(str(tmp_path), file_sink=False)
        handler = next(h for h in logging.getLogger().handlers
                       if getattr(h, "_llmlb_sink", False))
        assert handler.formatter._fmt == "%(levelname)s|%(message)s"
    finally:
        monkeypatch.delenv("LLMLB_LOG_FORMAT", raising=False)
        init_logging(str(tmp_path), file_sink=False)


def test_label_exposition_injects_worker_label():
    from llmlb_tpu.gateway.metrics import label_exposition

    text = (
        "# TYPE llmlb_gateway_requests_total counter\n"
        'llmlb_gateway_requests_total{route="/v1/x",status="200"} 5\n'
        "llmlb_gateway_active_requests 2\n"
    )
    out = label_exposition(text, "worker", "3")
    assert ('llmlb_gateway_requests_total{route="/v1/x",status="200",'
            'worker="3"} 5') in out
    assert 'llmlb_gateway_active_requests{worker="3"} 2' in out
    assert out.splitlines()[0].startswith("# TYPE")  # comments untouched


# ------------------------------------------------------------ real processes


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_so_reuseport_two_workers_serve_one_port(tmp_path):
    """The real thing: `serve --workers 2` forks two processes onto one
    port; /health answers, /metrics carries worker labels and merges the
    sibling's spooled series."""
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("SO_REUSEPORT unavailable on this platform")
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "LLMLB_DATA_DIR": str(tmp_path / "data"),
        "LLMLB_LOG_DIR": str(tmp_path / "logs"),
        "LLMLB_ADMIN_PASSWORD": "multiworker1",
        "LLMLB_METRICS_SPOOL_SECS": "0.3",
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "llmlb_tpu.gateway.server", "serve",
         "--host", "127.0.0.1", "--port", str(port), "--workers", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 30
        up = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            try:
                with urllib.request.urlopen(f"{base}/health", timeout=1) as r:
                    if r.status == 200:
                        up = True
                        break
            except OSError:
                time.sleep(0.2)
        assert up, (
            f"gateway never came up: "
            f"{proc.stderr.read().decode(errors='replace')[-2000:]}"
            if proc.poll() is not None else "gateway never answered /health"
        )
        # give both workers time to write a metrics spool, then scrape a
        # few times: whichever worker answers must include both workers
        time.sleep(1.0)
        saw_workers = set()
        for _ in range(6):
            with urllib.request.urlopen(f"{base}/metrics", timeout=2) as r:
                text = r.read().decode()
            for needle in ('worker="0"', 'worker="1"'):
                if needle in text:
                    saw_workers.add(needle)
            if len(saw_workers) == 2:
                break
            time.sleep(0.5)
        assert saw_workers == {'worker="0"', 'worker="1"'}, (
            f"merged /metrics missing worker series: {saw_workers}"
        )
        with urllib.request.urlopen(f"{base}/api/health", timeout=2) as r:
            body = json.loads(r.read().decode())
        assert body["worker"]["count"] == 2
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)


def test_forked_workers_all_answer_trace_lookup(tmp_path):
    """Regression: `/api/traces/{id}` used to 404 whenever SO_REUSEPORT
    handed the lookup to the worker that didn't serve the request. With
    the trace spool (gossip dir), EVERY worker must answer. Each urllib
    call opens a fresh connection, so repeated lookups land on both
    workers — one 404 fails the test."""
    if not hasattr(socket, "SO_REUSEPORT"):
        pytest.skip("SO_REUSEPORT unavailable on this platform")
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "LLMLB_DATA_DIR": str(tmp_path / "data"),
        "LLMLB_LOG_DIR": str(tmp_path / "logs"),
        "LLMLB_GOSSIP_DIR": str(tmp_path / "bus"),
        "LLMLB_ADMIN_PASSWORD": "multiworker1",
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "llmlb_tpu.gateway.server", "serve",
         "--host", "127.0.0.1", "--port", str(port), "--workers", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    base = f"http://127.0.0.1:{port}"

    def _post(path, payload, headers=None):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, {}

    try:
        deadline = time.monotonic() + 30
        up = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            try:
                with urllib.request.urlopen(f"{base}/health", timeout=1) as r:
                    if r.status == 200:
                        up = True
                        break
            except OSError:
                time.sleep(0.2)
        assert up, (
            f"gateway never came up: "
            f"{proc.stderr.read().decode(errors='replace')[-2000:]}"
            if proc.poll() is not None else "gateway never answered /health"
        )
        status, body = _post("/api/auth/login",
                             {"username": "admin",
                              "password": "multiworker1"})
        assert status == 200, f"login failed: {status}"
        auth = {"Authorization": f"Bearer {body['token']}"}

        # Any /v1 request is traced — even this unauthenticated 401 — and
        # exactly one worker serves (and spools) it.
        rid = "trace-fork-regress-1"
        status, _ = _post("/v1/chat/completions",
                          {"model": "nope", "messages": []},
                          headers={"X-Request-Id": rid})
        assert status in (401, 403, 404), status

        # 12 fresh connections: with one 404-ing worker the chance all 12
        # land on the serving sibling is 2^-12.
        for i in range(12):
            req = urllib.request.Request(f"{base}/api/traces/{rid}",
                                         headers=auth)
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 200, f"lookup {i} failed: {r.status}"
                got = json.loads(r.read().decode())
            assert got["trace_id"] == rid
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
