"""Native router core (native/router_core.cpp) vs the Python LoadManager.

The C++ core must be selection-for-selection and counter-for-counter
identical to the pure-Python implementation — it is the same state machine
(EMA α=0.2, unmeasured-first probe, telemetry tie-break, per-model
round-robin, active caps) compiled. A randomized workload is replayed
against both and every observable compared.
"""

import random

import pytest

from llmlb_tpu.gateway.balancer import LoadManager
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import (
    AcceleratorInfo,
    Endpoint,
    EndpointStatus,
    EndpointType,
    TpsApiKind,
)


def _endpoint(i: int, pressure: float | None = None,
              queue_depth: int = 0) -> Endpoint:
    ep = Endpoint(
        name=f"e{i}", base_url=f"http://e{i}:1", id=f"ep{i}",
        endpoint_type=EndpointType.OPENAI_COMPATIBLE,
        status=EndpointStatus.ONLINE,
    )
    if pressure is not None or queue_depth:
        import time

        ep.accelerator = AcceleratorInfo(
            hbm_used_bytes=int((pressure or 0.0) * 1_000_000),
            hbm_total_bytes=1_000_000,
            queue_depth=queue_depth,
            sampled_at=time.time(),
        )
    return ep


@pytest.fixture
def pair():
    cfgq = QueueConfig(max_active_per_endpoint=3)
    native = LoadManager(cfgq, use_native=True)
    if native._rc is None:
        pytest.skip("native router core not built")
    python = LoadManager(cfgq, use_native=False)
    return native, python


def test_randomized_parity(pair):
    native, python = pair
    rng = random.Random(7)
    endpoints = [_endpoint(i) for i in range(4)]
    model_names = ["m0", "m1"]
    leases = {"native": [], "python": []}

    for step in range(400):
        op = rng.random()
        if op < 0.35:
            eid = f"ep{rng.randrange(4)}"
            model = rng.choice(model_names)
            tokens = rng.randrange(1, 500)
            dur = rng.uniform(0.01, 3.0)
            for mgr in (native, python):
                mgr.update_tps(eid, model, TpsApiKind.CHAT, tokens, dur)
        elif op < 0.7:
            model = rng.choice(model_names)
            got_n = native.try_admit(endpoints, model, TpsApiKind.CHAT)
            got_p = python.try_admit(endpoints, model, TpsApiKind.CHAT)
            assert (got_n is None) == (got_p is None), f"step {step}"
            if got_n is not None:
                assert got_n[0].id == got_p[0].id, f"step {step}"
                leases["native"].append(got_n[1])
                leases["python"].append(got_p[1])
        elif op < 0.9:
            if leases["native"]:
                i = rng.randrange(len(leases["native"]))
                leases["native"].pop(i).complete()
                leases["python"].pop(i).complete()
        else:
            eid = f"ep{rng.randrange(4)}"
            native.clear_tps_for_endpoint(eid)
            python.clear_tps_for_endpoint(eid)

        for ep in endpoints:
            assert native.active_count(ep.id) == python.active_count(ep.id)
        for ep in endpoints:
            for model in model_names:
                tn = native.get_tps(ep.id, model, TpsApiKind.CHAT)
                tp = python.get_tps(ep.id, model, TpsApiKind.CHAT)
                if tp is None:
                    assert tn is None
                else:
                    assert tn == pytest.approx(tp, rel=1e-12)

    sn, sp = native.stats(), python.stats()
    assert sn["total_requests"] == sp["total_requests"]
    assert sn["active_requests"] == sp["active_requests"]
    assert sn["tracked_tps_keys"] == sp["tracked_tps_keys"]


def test_telemetry_tiebreak_parity(pair):
    """Unmeasured endpoints tie at +inf; telemetry must break the tie the
    same way on both paths (pressured endpoint demoted)."""
    native, python = pair
    eps = [
        _endpoint(0, pressure=0.99),   # heavily HBM-pressured
        _endpoint(1, pressure=0.2),    # healthy
    ]
    for _ in range(4):
        n = native.select_endpoint(eps, "m", TpsApiKind.CHAT)
        p = python.select_endpoint(eps, "m", TpsApiKind.CHAT)
        assert n.id == p.id == "ep1"


def test_round_robin_parity(pair):
    """All-unmeasured equal-penalty endpoints rotate identically."""
    native, python = pair
    eps = [_endpoint(i) for i in range(3)]
    seq_n = [native.select_endpoint(eps, "m", TpsApiKind.CHAT).id
             for _ in range(7)]
    seq_p = [python.select_endpoint(eps, "m", TpsApiKind.CHAT).id
             for _ in range(7)]
    assert seq_n == seq_p
    assert len(set(seq_n[:3])) == 3  # genuine rotation


def test_rejected_samples_create_no_keys(pair):
    """tokens<=0 / duration<=0 samples are dropped without creating a
    tracked key on either path (phantom keys skewed tracked_tps_keys)."""
    native, python = pair
    for mgr in pair:
        mgr.update_tps("ep0", "m", TpsApiKind.CHAT, 0, 1.0)
        mgr.update_tps("ep0", "m", TpsApiKind.CHAT, 10, 0.0)
    assert native.stats()["tracked_tps_keys"] == 0
    assert python.stats()["tracked_tps_keys"] == 0
    assert native.tps_snapshot() == {}
    assert python.tps_snapshot() == {}


def test_seed_and_snapshot_parity(pair):
    native, python = pair
    for mgr in pair:
        mgr.seed_tps("ep0", "m", TpsApiKind.CHAT, 123.456, samples=5)
        mgr.update_tps("ep0", "m", TpsApiKind.CHAT, 100, 1.0)
    sn = native.tps_snapshot()["ep0:m:chat"]
    sp = python.tps_snapshot()["ep0:m:chat"]
    assert sn["ema_tps"] == pytest.approx(sp["ema_tps"])
    assert sn["samples"] == sp["samples"]
