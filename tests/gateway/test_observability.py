"""Observability: request traces, X-Request-Id echo, gateway /metrics,
exposition-format validity, percentile interpolation, event-bus drop
accounting."""

import asyncio
import re

import pytest

from llmlb_tpu.engine.metrics import EngineMetrics, Histogram
from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.metrics import GatewayMetrics
from llmlb_tpu.gateway.tracing import (
    SPAN_ORDER,
    RequestTrace,
    TraceStore,
    mint_request_id,
)
from tests.support import GatewayHarness, MockOpenAIEndpoint

# ------------------------------------------------------- exposition validity

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(-?[0-9.eE+]+)$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def assert_valid_exposition(text: str) -> dict:
    """Parser-style validity check: every sample belongs to a `# TYPE`d
    family, histogram buckets are cumulative-monotonic with increasing
    edges ending at +Inf, and _count == +Inf bucket with _sum present.
    Returns the parsed histograms keyed by (family, labels)."""
    lines = text.splitlines()
    types: dict[str, str] = {}
    for ln in lines:
        if ln.startswith("# TYPE "):
            _, _, name, mtype = ln.split(" ")
            types[name] = mtype
    hists: dict = {}
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable sample line: {ln!r}"
        name, labels, value = m.group(1), m.group(2) or "", float(m.group(3))
        family = kind = None
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)]
            if name.endswith(suffix) and types.get(base) == "histogram":
                family, kind = base, suffix[1:]
                break
        if family is None:
            assert name in types, f"sample {name!r} has no # TYPE line"
            assert types[name] in ("counter", "gauge")
            continue
        labeldict = dict(_LABEL_RE.findall(labels))
        le = labeldict.pop("le", None)
        key = (family, tuple(sorted(labeldict.items())))
        entry = hists.setdefault(key, {"buckets": [], "sum": None,
                                       "count": None})
        if kind == "bucket":
            assert le is not None, f"{name} bucket without le label"
            entry["buckets"].append((le, value))
        elif kind == "sum":
            entry["sum"] = value
        else:
            entry["count"] = value
    for (family, labelkey), entry in hists.items():
        where = f"{family}{dict(labelkey)}"
        buckets = entry["buckets"]
        assert buckets, f"{where}: histogram with no buckets"
        assert buckets[-1][0] == "+Inf", f"{where}: missing +Inf bucket"
        values = [v for _, v in buckets]
        assert values == sorted(values), f"{where}: buckets not cumulative"
        edges = [float(le) for le, _ in buckets[:-1]]
        assert edges == sorted(edges) and len(set(edges)) == len(edges), (
            f"{where}: bucket edges not strictly increasing"
        )
        assert entry["count"] == values[-1], (
            f"{where}: _count {entry['count']} != +Inf bucket {values[-1]}"
        )
        assert entry["sum"] is not None, f"{where}: missing _sum"
    return hists


def test_engine_metrics_exposition_valid():
    m = EngineMetrics()
    for s in (0.004, 0.02, 0.3, 7.0, 45.0):
        m.record_ttft(s)
    for s in (0.002, 0.004, 0.08):
        m.record_itl(s)
    m.record_prefill_step(0.03)
    m.record_decode_step(0.006, active_slots=5)
    m.record_step_phases({"dispatch": 0.001, "compute": 0.004,
                          "fetch": 0.0002, "emit": 0.0001}, slow=True)
    m.record_request_done("stop")
    m.record_request_done("error")
    text = m.render(queue_depth=2, active_slots=5, num_slots=8)
    hists = assert_valid_exposition(text)
    families = {f for f, _ in hists}
    assert families == {
        "llmlb_engine_ttft_seconds", "llmlb_engine_itl_seconds",
        "llmlb_engine_prefill_step_seconds",
        "llmlb_engine_decode_step_seconds",
        "llmlb_engine_schema_compile_seconds",
        "llmlb_engine_step_phase_seconds",
        "llmlb_engine_handoff_latency_seconds",
    }
    assert "llmlb_engine_batch_occupancy 5" in text
    assert "llmlb_engine_slow_steps_total 1" in text
    # every phase of the taxonomy renders its own labeled series, observed
    # or not (dashboards see a complete label set)
    from llmlb_tpu.engine.stepstats import PHASES

    phase_labels = {dict(k).get("phase")
                    for f, k in hists if f == "llmlb_engine_step_phase_seconds"}
    assert phase_labels == set(PHASES)


def test_gateway_metrics_exposition_valid():
    g = GatewayMetrics()
    g.record_request("/v1/chat/completions", 200)
    g.record_request("/v1/chat/completions", 502)
    g.record_retry("chat")
    g.record_queue_timeout("m1")
    for s in (0.004, 0.2, 2.0):
        g.record_ttft("m1", "ep-a", s)
        g.record_e2e("m1", "ep-a", s * 2)
        g.record_queue_wait("m1", "ep-a", s / 4)
    g.record_e2e('weird"model\\name', "ep-b", 0.5)  # label escaping
    text = g.render(
        counters={"llmlb_gateway_dropped_events_total": 3},
        gauges={"llmlb_gateway_active_requests": 1},
    )
    hists = assert_valid_exposition(text)
    families = {f for f, _ in hists}
    assert families == {
        "llmlb_gateway_ttft_seconds", "llmlb_gateway_e2e_seconds",
        "llmlb_gateway_queue_wait_seconds",
    }
    assert 'llmlb_gateway_requests_total{route="/v1/chat/completions",status="502"} 1' in text
    assert 'llmlb_gateway_errors_total{route="/v1/chat/completions"} 1' in text
    assert 'llmlb_gateway_retries_total{api="chat"} 1' in text
    assert 'llmlb_gateway_queue_timeouts_total{model="m1"} 1' in text
    assert "llmlb_gateway_dropped_events_total 3" in text


# ---------------------------------------------------- percentile regression


def test_percentile_interpolates_below_first_edge():
    """A sample entirely below the first bucket edge must not report the
    edge itself (the old behavior)."""
    h = Histogram((1.0, 2.0, 4.0))
    for _ in range(4):
        h.observe(0.5)
    # uniform-within-bucket assumption: p50 of 4 samples in [0, 1] = 0.5
    assert h.percentile(50) == pytest.approx(0.5)
    assert h.percentile(100) == pytest.approx(1.0)


def test_percentile_matches_exact_on_uniform_sample():
    """Uniform data matches the linear-within-bucket assumption exactly, so
    interpolated percentiles should agree with nearest-rank percentiles."""
    sample = [i / 100.0 for i in range(1, 401)]  # 0.01 .. 4.00
    h = Histogram((0.5, 1.0, 2.0, 4.0))
    for v in sample:
        h.observe(v)
    for pct in (10, 25, 50, 75, 90, 99):
        exact = sample[int(len(sample) * pct / 100.0) - 1]
        assert h.percentile(pct) == pytest.approx(exact, rel=0.02), pct


def test_percentile_above_top_edge_reports_max():
    h = Histogram((1.0,))
    h.observe(9.5)
    assert h.percentile(99) == 9.5
    assert Histogram((1.0,)).percentile(50) is None


def test_percentile_empty_histogram_is_none_for_every_pct():
    """Empty histograms must report None at every percentile — not 0, not
    an edge — so /api/health consumers can tell 'no data' from 'fast'."""
    h = Histogram((0.5, 1.0, 2.0))
    for pct in (0.1, 1, 50, 99, 100):
        assert h.percentile(pct) is None
    # and an empty histogram still renders a valid exposition block
    m = EngineMetrics()
    assert_valid_exposition(m.render(queue_depth=0, active_slots=0,
                                     num_slots=1))


def test_percentile_single_bucket_interpolation():
    """All mass in ONE bucket: percentiles interpolate linearly between the
    bucket's lower and upper edge, never snap to an edge."""
    h = Histogram((1.0, 2.0, 4.0))
    for _ in range(10):
        h.observe(1.5)  # lands in (1.0, 2.0]
    # uniform-within-bucket: pN = 1.0 + N/100 * (2.0 - 1.0)
    assert h.percentile(10) == pytest.approx(1.1)
    assert h.percentile(50) == pytest.approx(1.5)
    assert h.percentile(90) == pytest.approx(1.9)
    # single-bucket histogram (one finite edge): same rule against lower=0
    h1 = Histogram((2.0,))
    h1.observe(0.5)
    h1.observe(1.5)
    assert h1.percentile(50) == pytest.approx(1.0)


# ------------------------------------------------------------- tracing unit


def test_mint_request_id_validates_shape():
    assert mint_request_id("abc-123_X.Z:9") == "abc-123_X.Z:9"
    assert mint_request_id(None) != mint_request_id(None)
    assert mint_request_id("bad id with spaces") != "bad id with spaces"
    assert mint_request_id("x" * 200) != "x" * 200


def test_trace_store_ring_bounded():
    store = TraceStore(capacity=3)
    for i in range(5):
        t = store.start(f"t{i}", "POST", "/v1/chat/completions")
        store.finish(t, 200)
    assert len(store) == 3
    assert store.get("t0") is None
    assert store.get("t4")["status"] == 200
    listed = store.list()
    assert [t["trace_id"] for t in listed] == ["t4", "t3", "t2"]
    assert store.list(limit=0) == []
    assert store.list(limit=-5) == []


def test_trace_store_reused_id_does_not_evict_live_trace():
    """Two concurrent requests with the same client-supplied id: the first
    one finishing must not remove the second's in-flight entry."""
    store = TraceStore(capacity=8)
    a = store.start("dup", "POST", "/v1/chat/completions")
    b = store.start("dup", "POST", "/v1/chat/completions")
    store.finish(a, 200)
    live = store.get("dup")
    assert live["in_flight"] is True  # b still observable
    store.finish(b, 200)
    assert store.get("dup")["in_flight"] is False
    assert len(store) == 2


def test_trace_spans_ordered_and_closed_on_finish():
    t = RequestTrace("id1", "POST", "/v1/chat/completions")
    t.begin("auth")
    t.end("auth")
    t.begin("admission")
    t.end("admission")
    t.begin("proxy")  # left open: finish() must close it
    t.finish(200)
    names = [s["name"] for s in t.spans]
    assert names[-1] == "done"
    starts = [s["start_ms"] for s in t.spans]
    assert starts == sorted(starts)
    assert all(s["duration_ms"] is not None and s["duration_ms"] >= 0
               for s in t.spans)


# ---------------------------------------------------------- SLO goodput


def test_record_slo_judges_against_targets():
    from llmlb_tpu.gateway.config import SloConfig

    cfg = SloConfig(ttft_target_s=0.5, itl_target_s=0.05,
                    per_model={"fast": (0.1, 0.01)})
    g = GatewayMetrics(slo=cfg)
    g.record_slo("m", 0.2, 0.01)          # met
    g.record_slo("m", 0.9, 0.01)          # ttft miss
    g.record_slo("m", 0.2, 0.2)           # itl miss
    g.record_slo("m", 0.9, 0.2)           # both miss
    g.record_slo("m", 0.2, None)          # non-streaming: TTFT only, met
    g.record_slo("fast", 0.2, None)       # per-model override: 0.1s → miss
    g.record_slo("m", None, None)         # no first byte: not judged
    text = g.render()
    assert 'llmlb_gateway_slo_eligible_total{model="m"} 5' in text
    assert 'llmlb_gateway_slo_met_total{model="m"} 2' in text
    assert 'llmlb_gateway_slo_ttft_miss_total{model="m"} 2' in text
    assert 'llmlb_gateway_slo_itl_miss_total{model="m"} 2' in text
    assert 'llmlb_gateway_goodput_ratio{model="m"} 0.4' in text
    assert 'llmlb_gateway_slo_ttft_miss_total{model="fast"} 1' in text
    summary = g.summary()
    assert summary["slo_eligible_total"] == 6
    assert summary["goodput_ratio"] == pytest.approx(2 / 6, abs=1e-4)


def test_record_slo_disabled_or_unconfigured_is_inert():
    from llmlb_tpu.gateway.config import SloConfig

    for g in (GatewayMetrics(),  # no config at all
              GatewayMetrics(slo=SloConfig(enabled=False))):
        g.record_slo("m", 0.1, 0.01)
        text = g.render()
        # families still render (dashboards never 404), at zero samples
        assert "# TYPE llmlb_gateway_slo_eligible_total counter" in text
        assert "llmlb_gateway_slo_eligible_total{" not in text
        assert "# TYPE llmlb_gateway_goodput_ratio gauge" in text


def test_slo_config_from_env_parses_overrides(monkeypatch):
    from llmlb_tpu.gateway.config import SloConfig

    monkeypatch.setenv("LLMLB_SLO_TTFT_MS", "1500")
    monkeypatch.setenv("LLMLB_SLO_ITL_MS", "80")
    monkeypatch.setenv("LLMLB_SLO_TARGETS",
                       '{"llama-3-8b": {"ttft_ms": 500, "itl_ms": 50}}')
    cfg = SloConfig.from_env()
    assert cfg.targets_for("other") == (1.5, 0.08)
    assert cfg.targets_for("llama-3-8b") == (0.5, 0.05)
    # malformed JSON degrades to defaults, never raises
    monkeypatch.setenv("LLMLB_SLO_TARGETS", "{not json")
    assert SloConfig.from_env().targets_for("llama-3-8b") == (1.5, 0.08)


# ------------------------------------------------------------ token timeline


def test_token_timeline_bounded_and_payload():
    from llmlb_tpu.gateway.tracing import TokenTimeline

    tl = TokenTimeline()
    for _ in range(TokenTimeline.MAX_MARKS + 10):
        tl.mark()
    assert tl.count == TokenTimeline.MAX_MARKS + 10
    assert len(tl.marks) == TokenTimeline.MAX_MARKS
    payload = tl.payload(tl.marks[0])
    assert payload["truncated"] is True
    assert payload["chunks"] == TokenTimeline.MAX_MARKS + 10
    assert payload["first_ms"] == 0.0
    assert payload["max_gap_ms"] >= 0.0
    assert len(payload["marks_ms"]) == TokenTimeline.MAX_MARKS


def test_trace_store_timeline_sampling_interval():
    store = TraceStore(capacity=4, timeline_interval=3)
    decisions = [store.sample_timeline() for _ in range(9)]
    assert decisions == [True, False, False] * 3
    assert not TraceStore(capacity=4,
                          timeline_interval=0).sample_timeline()


# -------------------------------------------------------- event bus drops


async def test_event_bus_counts_dropped_events():
    bus = DashboardEventBus(queue_size=2)
    sub_id, q = bus.subscribe()
    for i in range(5):
        bus.publish("TpsUpdated", {"i": i})
    await asyncio.sleep(0)  # run the call_soon_threadsafe callbacks
    assert bus.dropped_events(sub_id) == 3
    assert bus.dropped_events_total() == 3
    # the queue kept the NEWEST events (oldest were dropped)
    kept = [q.get_nowait()["data"]["i"] for _ in range(2)]
    assert kept == [3, 4]
    bus.unsubscribe(sub_id)
    assert bus.dropped_events(sub_id) == 0  # per-sub count dies with the sub
    assert bus.dropped_events_total() == 3  # total survives for /metrics


# ------------------------------------------------------------- end to end


async def test_request_id_echoed_and_trace_complete():
    """Acceptance: a completed chat request yields (a) an X-Request-Id
    response header, (b) an ordered auth→done trace with non-negative
    durations, (c) per-model TTFT/e2e/queue-wait histograms at /metrics
    that pass the exposition check."""
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"], name="ep-a")
        headers = dict(await gw.inference_headers())
        headers["X-Request-Id"] = "trace-abc-123"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1",
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=headers,
        )
        assert resp.status == 200, await resp.text()
        # (a) header echoed, client id reused
        assert resp.headers["X-Request-Id"] == "trace-abc-123"
        await resp.read()
        # the proxied upstream call carried the same id (engine joins trace)
        assert upstream.headers_seen[-1]["X-Request-Id"] == "trace-abc-123"

        # (b) the trace is served and well-formed
        t = await gw.client.get("/api/traces/trace-abc-123",
                                headers=await gw.admin_headers())
        assert t.status == 200
        trace = await t.json()
        assert trace["model"] == "m1"
        assert trace["endpoint_name"] == "ep-a"
        assert trace["status"] == 200
        names = [s["name"] for s in trace["spans"]]
        for expected in ("auth", "admission", "queue_wait", "endpoint_select",
                         "proxy", "first_token", "done"):
            assert expected in names, names
        assert names[0] == "auth" and names[-1] == "done"
        assert all(n in SPAN_ORDER for n in names)
        starts = [s["start_ms"] for s in trace["spans"]]
        assert starts == sorted(starts)
        assert all(s["duration_ms"] >= 0 for s in trace["spans"])

        lst = await gw.client.get("/api/traces",
                                  headers=await gw.admin_headers())
        assert lst.status == 200
        assert any(t["trace_id"] == "trace-abc-123"
                   for t in (await lst.json())["traces"])
        missing = await gw.client.get("/api/traces/nope",
                                      headers=await gw.admin_headers())
        assert missing.status == 404

        # (c) gateway /metrics: per-model histograms, valid exposition
        m = await gw.client.get("/metrics")
        assert m.status == 200
        text = await m.text()
        hists = assert_valid_exposition(text)
        for family in ("llmlb_gateway_ttft_seconds",
                       "llmlb_gateway_e2e_seconds",
                       "llmlb_gateway_queue_wait_seconds"):
            labelsets = [dict(k) for f, k in hists if f == family]
            assert any(ls.get("model") == "m1" and ls.get("endpoint") == "ep-a"
                       for ls in labelsets), (family, labelsets)
        assert 'llmlb_gateway_requests_total{route="/v1/chat/completions",status="200"} 1' in text
        assert "llmlb_gateway_dropped_events_total" in text

        # the dashboard overview carries the same figures as JSON
        ov = await gw.client.get("/api/dashboard/overview",
                                 headers=await gw.admin_headers())
        latency = (await ov.json())["latency"]
        assert latency["ttft_s"]["count"] >= 1
        assert latency["e2e_s"]["p50"] is not None
    finally:
        await upstream.stop()
        await gw.close()


async def test_request_id_on_error_paths_and_streams():
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"], name="ep-a")
        # error path: unauthenticated request still gets an id
        resp = await gw.client.post("/v1/chat/completions", json={})
        assert resp.status == 401
        assert resp.headers.get("X-Request-Id")
        # a malformed client id is replaced, not echoed
        resp = await gw.client.post(
            "/v1/chat/completions", json={},
            headers={"X-Request-Id": "bad id!! with spaces"},
        )
        assert resp.headers.get("X-Request-Id") not in (None,
                                                        "bad id!! with spaces")
        # streaming: header present on the prepared stream + decode span
        headers = dict(await gw.inference_headers())
        headers["X-Request-Id"] = "trace-stream-1"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "stream": True,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=headers,
        )
        assert resp.status == 200
        assert resp.headers["X-Request-Id"] == "trace-stream-1"
        body = await resp.text()
        assert "[DONE]" in body
        t = await gw.client.get("/api/traces/trace-stream-1",
                                headers=await gw.admin_headers())
        trace = await t.json()
        names = [s["name"] for s in trace["spans"]]
        assert "first_token" in names and "decode" in names
        # 404-model path records a trace too (finished at 404)
        headers["X-Request-Id"] = "trace-missing-model"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "nope",
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=headers,
        )
        assert resp.status == 404
        assert resp.headers["X-Request-Id"] == "trace-missing-model"
        t = await gw.client.get("/api/traces/trace-missing-model",
                                headers=await gw.admin_headers())
        assert (await t.json())["status"] == 404
    finally:
        await upstream.stop()
        await gw.close()


async def test_trace_completed_event_published():
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"])
        sub_id, queue = gw.state.events.subscribe()
        try:
            headers = dict(await gw.inference_headers())
            headers["X-Request-Id"] = "trace-ev-1"
            resp = await gw.client.post(
                "/v1/chat/completions",
                json={"model": "m1",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers=headers,
            )
            assert resp.status == 200
            await resp.read()
            event = None
            for _ in range(20):
                try:
                    candidate = queue.get_nowait()
                except asyncio.QueueEmpty:
                    await asyncio.sleep(0.01)
                    continue
                if candidate["type"] == "TraceCompleted":
                    event = candidate
                    break
            assert event is not None, "no TraceCompleted event seen"
            assert event["data"]["trace_id"] == "trace-ev-1"
            assert event["data"]["status"] == 200
        finally:
            gw.state.events.unsubscribe(sub_id)
    finally:
        await upstream.stop()
        await gw.close()


async def test_stream_trace_carries_token_timeline_and_goodput():
    """A streamed request's trace carries the sampled token timeline
    (first/last marks, max gap) and the gateway judges the request against
    its SLO targets — counters + goodput ratio visible in /metrics."""
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"], name="ep-a")
        headers = dict(await gw.inference_headers())
        headers["X-Request-Id"] = "trace-timeline-1"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "stream": True,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=headers,
        )
        assert resp.status == 200
        body = await resp.text()
        assert "[DONE]" in body

        t = await gw.client.get("/api/traces/trace-timeline-1",
                                headers=await gw.admin_headers())
        trace = await t.json()
        tl = trace.get("token_timeline")
        assert tl is not None, trace
        assert tl["chunks"] >= 1
        assert tl["marks_ms"] and tl["first_ms"] is not None
        assert tl["last_ms"] >= tl["first_ms"]
        assert tl["max_gap_ms"] >= 0.0
        assert tl["truncated"] is False

        # goodput: the mock upstream answers instantly, so the request met
        # its targets and the ledger says so
        m = await gw.client.get("/metrics")
        text = await m.text()
        assert 'llmlb_gateway_slo_eligible_total{model="m1"} 1' in text
        assert 'llmlb_gateway_slo_met_total{model="m1"} 1' in text
        assert 'llmlb_gateway_goodput_ratio{model="m1"} 1.0' in text

        # non-streaming requests are judged too (TTFT-only)
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1",
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=await gw.inference_headers(),
        )
        assert resp.status == 200
        await resp.read()
        text = await (await gw.client.get("/metrics")).text()
        assert 'llmlb_gateway_slo_eligible_total{model="m1"} 2' in text
    finally:
        await upstream.stop()
        await gw.close()


async def test_timeline_sampling_zero_disables_marks():
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"])
        gw.state.traces.timeline_interval = 0  # operator disabled sampling
        headers = dict(await gw.inference_headers())
        headers["X-Request-Id"] = "trace-no-tl"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "stream": True,
                  "messages": [{"role": "user", "content": "hi"}]},
            headers=headers,
        )
        assert resp.status == 200
        await resp.read()
        t = await gw.client.get("/api/traces/trace-no-tl",
                                headers=await gw.admin_headers())
        assert "token_timeline" not in await t.json()
    finally:
        await upstream.stop()
        await gw.close()


async def test_api_traces_endpoint_ring_wraparound():
    """/api/traces over HTTP with a tiny ring: older traces fall off, the
    buffered gauge tracks the ring size, and evicted ids 404."""
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"])
        # shrink the ring in place (handlers read state.traces live)
        gw.state.traces = TraceStore(capacity=3)
        headers = dict(await gw.inference_headers())
        for i in range(5):
            headers["X-Request-Id"] = f"wrap-{i}"
            resp = await gw.client.post(
                "/v1/chat/completions",
                json={"model": "m1",
                      "messages": [{"role": "user", "content": "hi"}]},
                headers=headers,
            )
            assert resp.status == 200
            await resp.read()
        lst = await gw.client.get("/api/traces",
                                  headers=await gw.admin_headers())
        ids = [t["trace_id"] for t in (await lst.json())["traces"]]
        assert ids == ["wrap-4", "wrap-3", "wrap-2"]
        gone = await gw.client.get("/api/traces/wrap-0",
                                   headers=await gw.admin_headers())
        assert gone.status == 404
        kept = await gw.client.get("/api/traces/wrap-4",
                                   headers=await gw.admin_headers())
        assert (await kept.json())["status"] == 200
        text = await (await gw.client.get("/metrics")).text()
        assert "llmlb_gateway_traces_buffered 3" in text
    finally:
        await upstream.stop()
        await gw.close()


async def test_api_key_permission_for_traces():
    gw = await GatewayHarness.create()
    try:
        resp = await gw.client.post(
            "/api/api-keys",
            json={"name": "mr", "permissions": ["metrics.read"]},
            headers=await gw.admin_headers(),
        )
        assert resp.status == 201
        key = (await resp.json())["api_key"]
        ok = await gw.client.get(
            "/api/traces", headers={"Authorization": f"Bearer {key}"}
        )
        assert ok.status == 200
        resp = await gw.client.post(
            "/api/api-keys",
            json={"name": "inf", "permissions": ["openai.inference"]},
            headers=await gw.admin_headers(),
        )
        key2 = (await resp.json())["api_key"]
        denied = await gw.client.get(
            "/api/traces", headers={"Authorization": f"Bearer {key2}"}
        )
        assert denied.status == 403
    finally:
        await gw.close()
