"""Gateway overload protection: per-key token buckets (429 + Retry-After),
weighted fair queuing, request deadlines, and slow-loris stream-write
timeouts (docs/scheduling.md). Tier-1, fully in-process.
"""

import asyncio
import dataclasses
import time

from llmlb_tpu.gateway.balancer import AdmissionQueue, LoadManager
from llmlb_tpu.gateway.config import QueueConfig, RateLimitConfig
from llmlb_tpu.gateway.faults import FaultInjector, FaultRule
from llmlb_tpu.gateway.ratelimit import RateLimiter, TokenBucket
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind
from tests.support import GatewayHarness, MockOpenAIEndpoint

CHAT = "/v1/chat/completions"


def _chat_body(model="mock-model", stream=False, **extra):
    body = {"model": model,
            "messages": [{"role": "user", "content": "ping"}], **extra}
    if stream:
        body["stream"] = True
    return body


# ------------------------------------------------------------ bucket units


def test_token_bucket_take_refill_and_retry_after():
    b = TokenBucket(rate_per_s=10.0, burst=2.0)
    now = time.monotonic()
    assert b.take(1.0, now) == 0.0
    assert b.take(1.0, now) == 0.0
    wait = b.take(1.0, now)  # empty: 1 token at 10/s = 0.1s away
    assert 0.09 <= wait <= 0.11
    assert b.take(1.0, now + 0.2) == 0.0  # refilled


def test_token_bucket_postpaid_charge_goes_negative():
    b = TokenBucket(rate_per_s=1.0, burst=5.0)
    now = time.monotonic()
    b.charge(20.0, now)  # completion tokens debit unconditionally
    wait = b.take(1.0, now)
    assert wait >= 15.0  # deep in debt: next request throttled hard


def test_ratelimiter_rps_and_overrides_and_worker_division():
    cfg = RateLimitConfig(requests_per_s=2.0, burst=2.0,
                          overrides={"bulk": {"rps": 1.0, "burst": 1.0,
                                              "tpm": 0.0}})
    rl = RateLimiter(cfg)
    assert rl.acquire("k1", "normal-key").allowed
    assert rl.acquire("k1", "normal-key").allowed
    refused = rl.acquire("k1", "normal-key")
    assert not refused.allowed and refused.reason == "requests"
    assert refused.retry_after_s > 0
    # override keyed by name: only 1 burst
    assert rl.acquire("k2", "bulk").allowed
    assert not rl.acquire("k2", "bulk").allowed
    # two workers: each enforces half the configured rate
    rl2 = RateLimiter(cfg, workers=2)
    assert rl2.acquire("k3", None).allowed
    assert not rl2.acquire("k3", None).allowed  # burst 2/2 = 1


def test_ratelimiter_tokens_per_minute_and_postpaid():
    cfg = RateLimitConfig(tokens_per_min=600.0)  # bucket burst = 600
    rl = RateLimiter(cfg)
    assert rl.acquire("k", None, est_tokens=500).allowed
    refused = rl.acquire("k", None, est_tokens=500)
    assert not refused.allowed and refused.reason == "tokens"
    assert refused.retry_after_s > 10  # 400 missing tokens at 10/s
    rl.charge_tokens("k", 1000)  # post-paid completion debit
    refused = rl.acquire("k", None, est_tokens=1)
    assert not refused.allowed and refused.retry_after_s > 60


# --------------------------------------------------------------- WFQ units


def _ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1")


async def _wfq_order(weights=None, wfq=True):
    """Park 3 waiters for tenant A then 1 for tenant B behind a
    single-slot endpoint; return the service order."""
    lm = LoadManager(QueueConfig(max_active_per_endpoint=1))
    q = AdmissionQueue(lm)
    q.wfq_enabled = wfq
    q.weights = weights or {}
    a = _ep("a")
    gate = await q.admit(lambda: [a], "m", TpsApiKind.CHAT, timeout_s=1.0)
    assert gate.admitted
    order: list[str] = []

    async def waiter(label: str, tenant: str):
        res = await q.admit(lambda: [a], "m", TpsApiKind.CHAT,
                            timeout_s=5.0, tenant=tenant,
                            weight=q.weight_for(tenant))
        assert res.admitted
        order.append(label)
        await asyncio.sleep(0.01)
        res.lease.complete()

    tasks = []
    for i in range(3):
        tasks.append(asyncio.create_task(waiter(f"A{i}", "A")))
        await asyncio.sleep(0.01)
    tasks.append(asyncio.create_task(waiter("B", "B")))
    await asyncio.sleep(0.01)
    assert q.queue_depth() == 4
    gate.lease.complete()
    await asyncio.gather(*tasks)
    return order


def test_wfq_interleaves_tenants():
    """The greedy tenant's 3 queued requests advance its virtual clock, so
    the light tenant's single request slots in right behind A's FIRST."""
    assert asyncio.run(_wfq_order()) == ["A0", "B", "A1", "A2"]


def test_wfq_weight_preference():
    assert asyncio.run(_wfq_order(weights={"B": 4.0})) == [
        "B", "A0", "A1", "A2"
    ]


def test_wfq_disabled_restores_fifo():
    assert asyncio.run(_wfq_order(wfq=False)) == ["A0", "A1", "A2", "B"]


# ------------------------------------------------------------- HTTP level


def test_ratelimit_429_with_retry_after_both_dialects():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            gw.state.ratelimit = RateLimiter(
                RateLimitConfig(requests_per_s=0.5, burst=1.0)
            )
            headers = await gw.inference_headers()
            ok = await gw.client.post(CHAT, json=_chat_body(),
                                      headers=headers)
            assert ok.status == 200
            refused = await gw.client.post(CHAT, json=_chat_body(),
                                           headers=headers)
            assert refused.status == 429
            assert int(refused.headers["Retry-After"]) >= 1
            body = await refused.json()
            assert body["error"]["type"] == "rate_limit_error"
            # Anthropic dialect: same buckets, Anthropic error shape
            key = await gw.inference_key()
            refused2 = await gw.client.post(
                "/v1/messages",
                json={"model": "mock-model", "max_tokens": 8,
                      "messages": [{"role": "user", "content": "hi"}]},
                headers={"x-api-key": key},
            )
            assert refused2.status == 429
            body2 = await refused2.json()
            assert body2["type"] == "error"
            assert body2["error"]["type"] == "rate_limit_error"
            assert "Retry-After" in refused2.headers
            summary = gw.state.metrics.summary()
            assert summary["ratelimit_rejections_total"] == 2
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_deadline_header_propagates_to_engine():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()
            headers["X-Request-Deadline-Ms"] = "5000"
            resp = await gw.client.post(CHAT, json=_chat_body(),
                                        headers=headers)
            assert resp.status == 200
            fwd = mock.headers_seen[-1]["X-Request-Deadline-Ms"]
            assert 0 < int(fwd) <= 5000
            # malformed header is a client error, not a proxy attempt
            bad = dict(await gw.inference_headers())
            bad["X-Request-Deadline-Ms"] = "soon"
            resp = await gw.client.post(CHAT, json=_chat_body(),
                                        headers=bad)
            assert resp.status == 400
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_deadline_sheds_queued_request_504():
    """A request whose deadline expires while queued for capacity is shed
    with 504 — before it burns a prefill — instead of waiting out the full
    queue timeout for a 503."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(reply_delay_s=1.0).start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            gw.state.load_manager.queue_config = QueueConfig(
                max_active_per_endpoint=1, queue_timeout_s=10.0,
            )
            headers = await gw.inference_headers()
            blocker = asyncio.create_task(
                gw.client.post(CHAT, json=_chat_body(), headers=headers)
            )
            await asyncio.sleep(0.1)  # occupy the single slot
            t0 = time.monotonic()
            h2 = dict(headers)
            h2["X-Request-Deadline-Ms"] = "150"
            shed = await gw.client.post(CHAT, json=_chat_body(), headers=h2)
            waited = time.monotonic() - t0
            assert shed.status == 504
            assert (await shed.json())["error"]["type"] == "timeout_error"
            assert waited < 1.0, f"shed took {waited:.2f}s (queue timeout?)"
            assert (await blocker).status == 200
            assert gw.state.metrics.summary()["deadline_shed_total"] == 1
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_stalled_reader_write_timeout_frees_stream():
    """Satellite: a client that stops draining the SSE stream (simulated by
    the stalled_reader fault inside the pump's guarded write) trips the
    write timeout — the stream aborts instead of pinning the slot until
    the inference timeout."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(tokens_per_reply=8,
                                        inter_chunk_delay_s=0.02).start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            gw.state.config = dataclasses.replace(
                gw.state.config, stream_write_timeout_s=0.2,
            )
            gw.state.faults = FaultInjector([
                FaultRule(kind="stalled_reader", latency_ms=5000,
                          after_bytes=1, max_fires=1),
            ])
            headers = await gw.inference_headers()
            t0 = time.monotonic()
            resp = await gw.client.post(
                CHAT, json=_chat_body(stream=True), headers=headers,
            )
            assert resp.status == 200
            raw = await resp.content.read()  # truncated at the stall point
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0, f"stall held the stream {elapsed:.1f}s"
            assert b"[DONE]" not in raw  # aborted, not completed
            summary = gw.state.metrics.summary()
            assert summary["stream_write_timeouts_total"] == 1
            assert summary["faults_injected_total"] == 1
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_goodput_by_priority_and_slo_labels():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint().start()
        try:
            gw.register_mock(mock.url, ["mock-model"])
            headers = await gw.inference_headers()
            for prio in ("high", "low", None):
                body = _chat_body()
                if prio is not None:
                    body["priority"] = prio
                resp = await gw.client.post(CHAT, json=body, headers=headers)
                assert resp.status == 200
            summary = gw.state.metrics.summary()
            by_prio = summary["goodput_by_priority"]
            assert by_prio.get("high") == 1.0
            assert by_prio.get("low") == 1.0
            assert by_prio.get("normal") == 1.0  # unset defaults to normal
            metrics = await gw.client.get("/metrics")
            text = await metrics.text()
            assert 'llmlb_gateway_goodput_by_priority{priority="high"}' in text
            assert "llmlb_gateway_ratelimit_rejections_total" in text
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())
