"""Prefix-affinity routing: same prompt head -> same endpoint while healthy;
fallback to normal scoring on unhealthy/absent/at-cap/evicted endpoints.

Parametrized over both LoadManager cores (pure Python and the native C++
router when built) — affinity lives on the Python side and must behave
identically in front of either scorer.
"""

import asyncio

import pytest

from llmlb_tpu.gateway.balancer import (
    PREFIX_AFFINITY_TTL_S,
    LoadManager,
    prefix_affinity_hash,
)
from llmlb_tpu.gateway.config import QueueConfig
from llmlb_tpu.gateway.types import Endpoint, TpsApiKind


def ep(name: str) -> Endpoint:
    return Endpoint(name=name, base_url=f"http://{name}:1234")


def native_available() -> bool:
    try:
        from llmlb_tpu.native import NativeRouterCore

        NativeRouterCore(0.2)
        return True
    except Exception:
        return False


CORES = [False] + ([True] if native_available() else [])


@pytest.fixture(params=CORES, ids=lambda n: "native" if n else "python")
def lm(request):
    return LoadManager(use_native=request.param)


def test_hash_is_stable_and_model_scoped():
    h1 = prefix_affinity_hash("m", "You are a helpful assistant. " * 20)
    h2 = prefix_affinity_hash("m", "You are a helpful assistant. " * 20)
    assert h1 == h2
    assert prefix_affinity_hash("other-model", "You are a helpful "
                                "assistant. " * 20) != h1
    # only the head participates: text diverging past the cap still matches
    base = "s" * 600
    assert prefix_affinity_hash("m", base + "A") == prefix_affinity_hash(
        "m", base + "B"
    )
    assert prefix_affinity_hash("m", "") is None
    # tiny prompts can never hit the engine's min cacheable prefix: no pin,
    # so TPS/telemetry placement keeps full control of them
    assert prefix_affinity_hash("m", "user:x") is None


def test_same_hash_sticks_to_one_endpoint(lm):
    endpoints = [ep("a"), ep("b"), ep("c")]  # all unmeasured: RR would rotate
    h = prefix_affinity_hash("m", "shared system prompt " * 10)
    first = lm.select_endpoint(endpoints, "m", prefix_hash=h)
    picks = [lm.select_endpoint(endpoints, "m", prefix_hash=h)
             for _ in range(5)]
    assert all(p is first for p in picks)
    stats = lm.affinity_stats()
    assert stats["hits_total"] == 5
    assert stats["misses_total"] == 1
    assert stats["entries"] == 1


def test_no_hash_keeps_round_robin(lm):
    endpoints = [ep("a"), ep("b"), ep("c")]
    picks = {lm.select_endpoint(endpoints, "m").name for _ in range(3)}
    assert picks == {"a", "b", "c"}


def test_distinct_hashes_spread_while_sticking(lm):
    """Different prefixes may land on different endpoints (RR underneath),
    but each prefix individually stays put."""
    endpoints = [ep("a"), ep("b")]
    h1 = prefix_affinity_hash("m", "prefix one " * 12)
    h2 = prefix_affinity_hash("m", "prefix two " * 12)
    e1 = lm.select_endpoint(endpoints, "m", prefix_hash=h1)
    e2 = lm.select_endpoint(endpoints, "m", prefix_hash=h2)
    assert e1 is not e2  # RR assigned the second prefix to the other engine
    assert lm.select_endpoint(endpoints, "m", prefix_hash=h1) is e1
    assert lm.select_endpoint(endpoints, "m", prefix_hash=h2) is e2


def test_fallback_when_sticky_endpoint_disappears(lm):
    """Unhealthy/removed endpoints are not in the candidate list; the hash
    re-pins to whatever healthy endpoint wins."""
    a, b = ep("a"), ep("b")
    h = prefix_affinity_hash("m", "pinned prompt " * 10)
    sticky = lm.select_endpoint([a, b], "m", prefix_hash=h)
    survivor = b if sticky is a else a
    got = lm.select_endpoint([survivor], "m", prefix_hash=h)
    assert got is survivor
    # re-pinned: the survivor now holds the affinity even among both
    assert lm.select_endpoint([a, b], "m", prefix_hash=h) is survivor


@pytest.mark.parametrize("use_native", CORES,
                         ids=lambda n: "native" if n else "python")
def test_fallback_when_sticky_endpoint_at_cap(use_native):
    lm = LoadManager(QueueConfig(max_active_per_endpoint=1),
                     use_native=use_native)
    a, b = ep("a"), ep("b")
    h = prefix_affinity_hash("m", "hot prompt " * 12)
    got = lm.try_admit([a, b], "m", TpsApiKind.CHAT, prefix_hash=h)
    assert got is not None
    sticky, lease = got
    other = b if sticky is a else a
    # sticky endpoint holds its only slot; the same hash must overflow
    got2 = lm.try_admit([a, b], "m", TpsApiKind.CHAT, prefix_hash=h)
    assert got2 is not None
    assert got2[0] is other
    lease.fail()
    got2[1].fail()


def test_affinity_cleared_on_endpoint_failure(lm):
    a, b = ep("a"), ep("b")
    h = prefix_affinity_hash("m", "flapping prompt " * 10)
    sticky = lm.select_endpoint([a, b], "m", prefix_hash=h)
    lm.clear_tps_for_endpoint(sticky.id)
    assert lm.affinity_stats()["entries"] == 0
    other = b if sticky is a else a
    # next selection re-learns; with the old pin gone RR moves on
    assert lm.select_endpoint([other], "m", prefix_hash=h) is other


def test_affinity_entry_expires(lm):
    a, b = ep("a"), ep("b")
    h = prefix_affinity_hash("m", "stale prompt " * 12)
    sticky = lm.select_endpoint([a, b], "m", prefix_hash=h)
    assert lm.select_endpoint([a, b], "m", prefix_hash=h) is sticky
    # age the pin past the TTL: the next lookup must treat it as a miss
    # (re-scored, re-pinned) instead of steering to a long-dead prefix
    key = ("m", h)
    eid, ts, ver = lm._affinity[key]
    lm._affinity[key] = (eid, ts - PREFIX_AFFINITY_TTL_S - 1, ver)
    misses_before = lm.affinity_stats()["misses_total"]
    got = lm.select_endpoint([a, b], "m", prefix_hash=h)
    assert got is not None
    assert lm.affinity_stats()["misses_total"] == misses_before + 1


def test_affinity_map_is_bounded(lm, monkeypatch):
    import llmlb_tpu.gateway.balancer as balancer_mod

    monkeypatch.setattr(balancer_mod, "PREFIX_AFFINITY_CAPACITY", 8)
    endpoints = [ep("a"), ep("b")]
    for i in range(50):
        h = prefix_affinity_hash("m", f"unique prefix {i} " * 10)
        lm.select_endpoint(endpoints, "m", prefix_hash=h)
    assert lm.affinity_stats()["entries"] <= 8


async def _admit_with_hash(lm, endpoints, h):
    result = await _make_admission(lm).admit(
        lambda: endpoints, "m", TpsApiKind.CHAT, timeout_s=0.2, prefix_hash=h
    )
    return result


def _make_admission(lm):
    from llmlb_tpu.gateway.balancer import AdmissionQueue

    return AdmissionQueue(lm)


def test_admission_queue_passes_prefix_hash(lm):
    async def run():
        endpoints = [ep("a"), ep("b"), ep("c")]
        h = prefix_affinity_hash("m", "queued prompt " * 10)
        r1 = await _admit_with_hash(lm, endpoints, h)
        assert r1.admitted
        r2 = await _admit_with_hash(lm, endpoints, h)
        assert r2.admitted
        assert r2.endpoint is r1.endpoint
        r1.lease.complete()
        r2.lease.complete()

    asyncio.run(run())


def test_gateway_routes_shared_prefix_to_one_upstream():
    """Full proxy path: two mock engines, repeated chat bodies sharing a
    system prompt — every request must reach the SAME upstream, and the
    affinity counters must appear in the gateway /metrics exposition."""
    from tests.support import GatewayHarness, MockOpenAIEndpoint

    async def run():
        gw = await GatewayHarness.create()
        up1 = await MockOpenAIEndpoint(model="m").start()
        up2 = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(up1.url, ["m"], name="up1")
            gw.register_mock(up2.url, ["m"], name="up2")
            headers = dict(await gw.inference_headers())
            system = "You are a careful reviewer. " * 8
            for i in range(6):
                resp = await gw.client.post("/v1/chat/completions", json={
                    "model": "m",
                    "messages": [
                        {"role": "system", "content": system},
                        {"role": "user", "content": f"question {i}"},
                    ],
                }, headers=headers)
                assert resp.status == 200, await resp.text()
                await resp.read()
            counts = (len(up1.requests_seen), len(up2.requests_seen))
            assert sorted(counts) == [0, 6], counts  # all stuck to one engine

            text = await (await gw.client.get("/metrics")).text()
            assert "llmlb_gateway_prefix_affinity_hits_total 5" in text
            assert "llmlb_gateway_prefix_affinity_misses_total 1" in text
            assert "llmlb_gateway_prefix_affinity_evictions_total 0" in text
            assert "llmlb_gateway_prefix_affinity_entries 1" in text
        finally:
            await up1.stop()
            await up2.stop()
            await gw.close()

    asyncio.run(run())
