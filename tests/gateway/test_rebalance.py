"""Proactive live-stream rebalancing (gateway/rebalance.py + the pump).

Two layers, both tier-1:

- Planner unit tests drive `Rebalancer.tick()` against fake telemetry and
  assert the safety rails directly: hysteresis bands, the migration
  budget, the per-stream window, drain evacuation and the SLO goodput
  gate. No sleeps — ticks are explicit.
- End-to-end migration tests run the real pump (GatewayHarness + two
  MockResumableEndpoints): a directive lands mid-stream and the client
  sees ONE uninterrupted token-identical SSE response while the stream
  re-homes through /v1/kv/export(park) → /v1/resume. Refused targets,
  unparkable origins, a target dying right after adoption (falls back to
  the reactive PR 12 resume, victim booked exactly once) and
  LLMLB_REBALANCE=0 bit-compatibility are each pinned.
"""

import asyncio
import json
import os
import time

from llmlb_tpu.gateway.faults import FaultRule
from llmlb_tpu.gateway.rebalance import (
    RebalanceConfig,
    Rebalancer,
    StreamDirectory,
)
from llmlb_tpu.gateway.resilience import BreakerState
from llmlb_tpu.gateway.types import AcceleratorInfo, EndpointType
from tests.support import (
    GatewayHarness,
    MockResumableEndpoint,
    assert_sse_protocol,
)

CHAT = "/v1/chat/completions"

SCRIPT = list(range(100, 160))  # long enough to land a directive mid-stream
FULL_TEXT = "".join(MockResumableEndpoint.text_of(t) for t in SCRIPT)


# ------------------------------------------------------------ planner fakes


class FakeEp:
    def __init__(self, eid, *, active=0, queue=0, slots=8, draining=False):
        self.id = eid
        self.endpoint_type = EndpointType.TPU
        self.accelerator = AcceleratorInfo(
            accelerator="tpu", num_slots=slots, active_slots=active,
            queue_depth=queue, draining=draining,
        )


class FakeRegistry:
    def __init__(self, eps):
        self.eps = eps

    def list_online(self):
        return list(self.eps)


class FakeLoad:
    def active_count(self, eid):
        return 0

    def tps_snapshot(self):
        return {}


class FakeMetrics:
    def __init__(self, goodput=None):
        self.goodput = goodput
        self.calls = []

    def record_rebalance_migration(self, reason, outcome):
        self.calls.append((reason, outcome))

    def summary(self):
        return {"goodput_ratio": self.goodput}


class FakeBus:
    def __init__(self):
        self.published = []

    def publish(self, kind, data, **kw):
        self.published.append((kind, data))


def _planner(eps, *, metrics=None, config=None, directory=None):
    bus = FakeBus()
    reb = Rebalancer(
        FakeRegistry(eps), FakeLoad(),
        directory or StreamDirectory(RebalanceConfig()),
        metrics=metrics, gossip=bus, config=config or RebalanceConfig(),
    )
    return reb, bus


# ------------------------------------------------------- planner unit tests


def test_hotspot_needs_consecutive_hot_ticks():
    """Hysteresis: one hot sample never moves a stream; the second
    consecutive one does, and the directive goes out over gossip."""
    hot = FakeEp("hot", active=8, queue=3)      # score 11/8, queue > 0
    idle = FakeEp("idle", active=0, queue=0)    # score 0
    reb, bus = _planner([hot, idle])
    reb.tick()
    assert bus.published == [] and reb.directives_total == 0
    reb.tick()
    assert reb.directives_total == 1
    assert bus.published == [("migrate", {
        "eid": "hot", "target": "idle", "reason": "hotspot",
        "max_streams": 1, "directive_id": 1,
    })]


def test_hysteresis_resets_on_a_cool_tick():
    """hot, cool, hot is NOT two consecutive hot ticks."""
    hot = FakeEp("hot", active=8, queue=3)
    idle = FakeEp("idle")
    reb, bus = _planner([hot, idle])
    reb.tick()
    hot.accelerator = AcceleratorInfo(num_slots=8, active_slots=1)  # cools
    reb.tick()
    hot.accelerator = AcceleratorInfo(num_slots=8, active_slots=8,
                                      queue_depth=3)  # hot again
    reb.tick()
    assert bus.published == []  # counter restarted at 1
    reb.tick()
    assert reb.directives_total == 1


def test_no_migration_between_the_bands():
    """A source above low but below high water is left alone, and so is a
    hot source when every other engine is also above the low band."""
    warm = FakeEp("warm", active=5, queue=0)    # 0.625: between bands
    idle = FakeEp("idle")
    reb, bus = _planner([warm, idle])
    reb.tick()
    reb.tick()
    assert bus.published == []
    busy = FakeEp("busy", active=8, queue=2)
    half = FakeEp("half", active=4, queue=0)    # 0.5 > low_water 0.4
    reb2, bus2 = _planner([busy, half])
    reb2.tick()
    reb2.tick()
    assert bus2.published == []


def test_goodput_gate_blocks_queueless_hotspots():
    """High occupancy with an empty queue and healthy (or unknown) goodput
    is just good utilization — no churn until SLOs measurably hurt."""
    hot = FakeEp("hot", active=8, queue=0)
    idle = FakeEp("idle")
    metrics = FakeMetrics(goodput=None)
    reb, bus = _planner([hot, idle], metrics=metrics)
    reb.tick()
    reb.tick()
    reb.tick()
    assert bus.published == []  # unknown goodput never justifies churn
    metrics.goodput = 0.80      # now the fleet is visibly missing SLOs
    reb.tick()
    assert reb.directives_total == 1
    assert bus.published[0][1]["reason"] == "hotspot"


def test_budget_per_minute_records_skipped():
    """Once the per-minute budget is spent, directives record `skipped`
    instead of issuing — thrash is bounded even under sustained heat."""
    hot = FakeEp("hot", active=8, queue=3)
    idle = FakeEp("idle")
    metrics = FakeMetrics()
    cfg = RebalanceConfig(per_minute=1)
    reb, bus = _planner([hot, idle], metrics=metrics, config=cfg)
    reb.tick(), reb.tick()
    assert reb.directives_total == 1
    reb.tick(), reb.tick()  # still hot: second directive wants to issue
    assert reb.directives_total == 1
    assert reb.skipped_budget_total == 1
    assert ("hotspot", "skipped") in metrics.calls
    assert len(bus.published) == 1


def test_budget_max_concurrent_counts_inflight():
    """Streams already pending/migrating count against max_concurrent."""
    directory = StreamDirectory(RebalanceConfig())
    for i in range(2):
        directory.register(f"r{i}", "m", "hot")
    assert directory.apply_directive("hot", "idle", "drain", 2, 1) == 2
    assert directory.inflight() == 2
    hot = FakeEp("hot", active=8, queue=3)
    idle = FakeEp("idle")
    reb, bus = _planner([hot, idle], metrics=FakeMetrics(),
                        config=RebalanceConfig(max_concurrent=2),
                        directory=directory)
    reb.tick(), reb.tick()
    assert reb.directives_total == 0 and reb.skipped_budget_total == 1


def test_drain_evacuation_targets_least_loaded():
    """A draining engine gets its streams moved NOW (reason=drain), to the
    lowest-scoring healthy engine, budget-paced."""
    going = FakeEp("going", active=4, draining=True)
    busy = FakeEp("busy", active=6)
    calm = FakeEp("calm", active=1)
    directory = StreamDirectory(RebalanceConfig())
    handle = directory.register("r1", "m", "going")
    reb, bus = _planner([going, busy, calm], directory=directory)
    reb.tick()
    assert bus.published == [("migrate", {
        "eid": "going", "target": "calm", "reason": "drain",
        "max_streams": 2, "directive_id": 1,
    })]
    assert handle.pending == ("calm", "drain", 1)


def test_stream_window_blocks_repeat_migration():
    """The same stream is never marked twice within stream_window_s —
    regardless of the outcome of the first attempt."""
    directory = StreamDirectory(RebalanceConfig(stream_window_s=60.0))
    handle = directory.register("r1", "m", "a")
    assert directory.apply_directive("a", "b", "hotspot", 1, 1) == 1
    assert directory.claim(handle) == ("b", "hotspot", 1)
    directory.note_outcome(handle, success=True, target="b")
    assert handle.endpoint_id == "b" and handle.migrations == 1
    assert directory.apply_directive("b", "a", "hotspot", 1, 2) == 0
    # outside the window it becomes eligible again
    handle.last_migrate_at = time.monotonic() - 61.0
    assert directory.apply_directive("b", "a", "hotspot", 1, 3) == 1


def test_directive_racing_natural_finish_dies_unclaimed():
    """Unregister (stream finished) wins the race: a pending directive is
    dropped, claim() returns None, nothing is accounted."""
    directory = StreamDirectory(RebalanceConfig())
    handle = directory.register("r1", "m", "a")
    assert directory.apply_directive("a", "b", "drain", 4, 1) == 1
    directory.unregister(handle)
    assert directory.claim(handle) is None
    assert directory.inflight() == 0
    assert directory.snapshot()["streams"] == 0


def test_disabled_directory_registers_nothing():
    directory = StreamDirectory(RebalanceConfig(enabled=False))
    assert directory.register("r1", "m", "a") is None
    assert directory.counts() == {}


def test_oldest_streams_evacuate_first():
    directory = StreamDirectory(RebalanceConfig())
    young = directory.register("young", "m", "a")
    old = directory.register("old", "m", "a")
    old.started_at -= 100.0
    assert directory.apply_directive("a", "b", "drain", 1, 1) == 1
    assert old.pending is not None and young.pending is None


# --------------------------------------------------------- e2e: the pump


def _chat_body():
    return {"model": "m", "stream": True,
            "messages": [{"role": "user", "content": "ping"}]}


def _openai_stream_text(body: bytes) -> str:
    parts = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            continue
        try:
            obj = json.loads(data)
        except ValueError:
            continue
        for choice in obj.get("choices") or []:
            content = (choice.get("delta") or {}).get("content")
            if isinstance(content, str):
                parts.append(content)
    return "".join(parts)


async def _migration_pair(gw, *, delay_s=0.01):
    """Two slow resumable tpu:// mocks + resilience, resume armed."""
    from llmlb_tpu.gateway.config import ResilienceConfig
    from llmlb_tpu.gateway.faults import FaultInjector
    from llmlb_tpu.gateway.resilience import ResilienceManager

    a = await MockResumableEndpoint(
        model="m", script=SCRIPT, inter_chunk_delay_s=delay_s).start()
    b = await MockResumableEndpoint(
        model="m", script=SCRIPT, inter_chunk_delay_s=delay_s).start()
    ep_a = gw.register_mock(a.url, ["m"], endpoint_type=EndpointType.TPU,
                            name="eng-a")
    ep_b = gw.register_mock(b.url, ["m"], endpoint_type=EndpointType.TPU,
                            name="eng-b")
    cfg = ResilienceConfig(backoff_base_s=0.001, backoff_cap_s=0.002,
                           failover_queue_timeout_s=0.3,
                           breaker_failure_threshold=3)
    manager = ResilienceManager(cfg, metrics=gw.state.metrics,
                                events=gw.state.events,
                                registry=gw.state.registry)
    gw.state.resilience = manager
    gw.state.load_manager.resilience = manager
    gw.state.faults = FaultInjector()
    return a, b, ep_a, ep_b, manager


async def _wait_for(pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(0.005)
    return False


async def _start_stream_and_directive(gw, mocks, eps, *, target_status=True):
    """POST a streaming chat, wait until it is live with some committed
    tokens, then issue a hotspot directive away from its origin. Returns
    (response, origin_mock, target_mock, origin_ep, target_ep)."""
    headers = await gw.inference_headers()
    r = await gw.client.post(CHAT, json=_chat_body(), headers=headers)
    assert r.status == 200, await r.text()
    assert await _wait_for(lambda: len(gw.state.streams._streams) == 1)
    handle = next(iter(gw.state.streams._streams.values()))
    # let a few committed tokens accumulate before moving the stream
    await asyncio.sleep(0.12)
    (ep_a, ep_b), (a, b) = eps, mocks
    origin_ep, target_ep = ((ep_a, ep_b) if handle.endpoint_id == ep_a.id
                            else (ep_b, ep_a))
    origin, target = (a, b) if origin_ep is ep_a else (b, a)
    marked = gw.state.streams.apply_directive(
        origin_ep.id, target_ep.id, "hotspot", 1, 1)
    assert marked == 1
    return r, origin, target, origin_ep, target_ep


def test_proactive_migration_token_identical():
    """The headline contract: a hotspot directive re-homes a LIVE stream
    through park-export + resume and the client sees one uninterrupted
    token-identical response — no error frame, no resume accounting (this
    was planning, not failure), the origin parked exactly once."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _migration_pair(gw)
            r, origin, target, origin_ep, target_ep = (
                await _start_stream_and_directive(
                    gw, (a, b), (ep_a, ep_b)))
            body = await r.read()
            assert b"event: error" not in body
            assert_sse_protocol(body, "openai")
            assert _openai_stream_text(body) == FULL_TEXT
            # the origin was asked to park + export, the target to adopt
            assert [c.get("park") for c in origin.export_calls] == [True]
            assert len(target.resume_calls) == 1
            committed = target.resume_calls[0]["committed_ids"]
            assert committed == SCRIPT[:len(committed)] and committed
            # the exported KV pages rode the resume body verbatim
            assert target.resume_calls[0]["kv_pages"] == {
                "mock": True, "park": True}
            summary = gw.state.metrics.summary()
            assert summary["rebalance_migrations"] == {"hotspot/success": 1}
            # migration is NOT failure: no resume outcomes, no
            # interruptions, both breakers untouched
            assert summary["stream_resumes"] == {}
            assert summary["stream_interruptions_total"] == 0
            assert manager.state_of(origin_ep.id) == BreakerState.CLOSED
            assert manager.state_of(target_ep.id) == BreakerState.CLOSED
            # the stream finished and unregistered cleanly
            assert gw.state.streams.snapshot()["streams"] == 0
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_target_refuses_stream_stays_on_origin():
    """A target that rejects the adopt aborts the migration instantly and
    invisibly: the SAME origin connection keeps streaming, outcome is
    `refused`, nobody's breaker or failure ledger moves."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _migration_pair(gw)
            a.resume_fail_with = 503
            b.resume_fail_with = 503
            r, origin, target, origin_ep, target_ep = (
                await _start_stream_and_directive(
                    gw, (a, b), (ep_a, ep_b)))
            body = await r.read()
            assert b"event: error" not in body
            assert _openai_stream_text(body) == FULL_TEXT
            summary = gw.state.metrics.summary()
            assert summary["rebalance_migrations"] == {"hotspot/refused": 1}
            assert summary["stream_resumes"] == {}
            outcomes = gw.state.load_manager.endpoint_outcomes()
            assert outcomes.get(target_ep.id, {}).get("failures", 0) == 0
            assert manager.state_of(target_ep.id) == BreakerState.CLOSED
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_origin_unparkable_aborts_untouched():
    """If the origin cannot export (old build, park refused), the
    migration aborts before the target is ever contacted."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _migration_pair(gw)
            a.export_fail_with = 404
            b.export_fail_with = 404
            r, origin, target, origin_ep, target_ep = (
                await _start_stream_and_directive(
                    gw, (a, b), (ep_a, ep_b)))
            body = await r.read()
            assert b"event: error" not in body
            assert _openai_stream_text(body) == FULL_TEXT
            assert target.resume_calls == []
            summary = gw.state.metrics.summary()
            assert summary["rebalance_migrations"] == {"hotspot/aborted": 1}
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_target_dies_after_adopt_falls_back_to_reactive_resume():
    """The adopting engine dies mid-stream AFTER a successful migration:
    the reactive resume path (PR 12) takes over, books the victim (the
    migration target) exactly once, and the client still gets the full
    token-identical text."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _migration_pair(gw)
            # cut the FIRST /v1/resume response (the migration adopt)
            # after a few frames; the reactive re-resume is the second
            # /v1/resume stream and is left alone (max_fires=1)
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="resume",
                after_bytes=600, max_fires=1,
            ))
            r, origin, target, origin_ep, target_ep = (
                await _start_stream_and_directive(
                    gw, (a, b), (ep_a, ep_b)))
            body = await r.read()
            assert b"event: error" not in body
            assert_sse_protocol(body, "openai")
            assert _openai_stream_text(body) == FULL_TEXT
            summary = gw.state.metrics.summary()
            assert summary["rebalance_migrations"] == {"hotspot/success": 1}
            # the reactive path fired once, against the migration target
            assert summary["stream_resumes"] == {"success": 1}
            outcomes = gw.state.load_manager.endpoint_outcomes()
            to = outcomes[target_ep.id]
            assert to["stream_interruptions"] == 1
            assert to["failures"] == 1
            # the origin was never booked for the planned hand-off
            oo = outcomes[origin_ep.id]
            assert oo.get("stream_interruptions", 0) == 0
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_rebalance_disabled_is_bit_compatible():
    """LLMLB_REBALANCE=0: streams never register with the directory, a
    directive marks nothing, and the stream is byte-identical to the
    pre-rebalancer gateway."""
    async def run():
        os.environ["LLMLB_REBALANCE"] = "0"
        try:
            gw = await GatewayHarness.create()
        finally:
            del os.environ["LLMLB_REBALANCE"]
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _migration_pair(gw)
            assert gw.state.streams.register("x", "m", ep_a.id) is None
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200
            await asyncio.sleep(0.05)
            assert gw.state.streams._streams == {}
            assert gw.state.streams.apply_directive(
                ep_a.id, ep_b.id, "hotspot", 1, 1) == 0
            assert gw.state.streams.apply_directive(
                ep_b.id, ep_a.id, "hotspot", 1, 1) == 0
            body = await r.read()
            assert b"event: error" not in body
            assert _openai_stream_text(body) == FULL_TEXT
            summary = gw.state.metrics.summary()
            assert summary["rebalance_migrations"] == {}
            assert a.export_calls == [] and b.export_calls == []
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())
