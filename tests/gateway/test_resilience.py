"""Resilience layer: failover retries, circuit breaker, fault injection.

The chaos scenarios run entirely in-process: faults.py injects
connect-refused / synthetic 5xx / mid-stream cuts at the proxy's HTTP
boundary, so no real sockets die on cue and every test is deterministic.
Tier-1 (fast, no TPU).
"""

import asyncio
import json
import time

from llmlb_tpu.gateway.config import QueueConfig, ResilienceConfig
from llmlb_tpu.gateway.faults import FaultInjector, FaultRule
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.resilience import (
    BreakerState,
    ResilienceManager,
    RetryBudget,
    backoff_delay,
)
from llmlb_tpu.gateway.types import EndpointStatus
from tests.support import (
    GatewayHarness,
    MockOpenAIEndpoint,
    assert_sse_protocol,
)

CHAT = "/v1/chat/completions"


def _chat_body(model="m", stream=False):
    body = {"model": model,
            "messages": [{"role": "user", "content": "ping"}]}
    if stream:
        body["stream"] = True
    return body


def _set_resilience(gw, **overrides) -> ResilienceManager:
    """Swap in a ResilienceManager with test-tuned knobs (tiny backoff,
    small thresholds) without touching process env."""
    cfg = ResilienceConfig(**{
        "backoff_base_s": 0.001, "backoff_cap_s": 0.002,
        "failover_queue_timeout_s": 0.3, **overrides,
    })
    manager = ResilienceManager(
        cfg, metrics=gw.state.metrics, events=gw.state.events,
        registry=gw.state.registry,
    )
    gw.state.resilience = manager
    gw.state.load_manager.resilience = manager
    return manager


# --------------------------------------------------------------- unit tests


def test_breaker_trips_after_threshold_and_reopens():
    m = ResilienceManager(ResilienceConfig(
        breaker_failure_threshold=3, breaker_open_s=0.05,
        breaker_open_max_s=0.5,
    ))
    eid = "ep1"
    assert m.allow(eid)
    for _ in range(2):
        m.record_failure(eid)
    assert m.state_of(eid) == BreakerState.CLOSED and m.allow(eid)
    m.record_failure(eid)  # third strike trips
    assert m.state_of(eid) == BreakerState.OPEN
    assert not m.allow(eid)

    time.sleep(0.06)
    # open interval elapsed: lazily half-open, one probe admitted
    assert m.allow(eid)
    assert m.state_of(eid) == BreakerState.HALF_OPEN
    m.on_admit(eid)
    assert not m.allow(eid)  # probe slot consumed

    m.record_failure(eid, "probe failed")  # probe fails: re-open, doubled
    assert m.state_of(eid) == BreakerState.OPEN
    info = m.breaker_info(eid)
    assert 0.05 < info["retry_after_s"] <= 0.5

    time.sleep(0.11)
    assert m.allow(eid)
    m.on_admit(eid)
    m.record_success(eid)  # probe succeeds: closed, streak cleared
    assert m.state_of(eid) == BreakerState.CLOSED
    assert m.breaker_info(eid)["consecutive_failures"] == 0


def test_breaker_success_resets_consecutive_failures():
    m = ResilienceManager(ResilienceConfig(breaker_failure_threshold=3))
    for _ in range(2):
        m.record_failure("e")
    m.record_success("e")
    for _ in range(2):
        m.record_failure("e")
    assert m.state_of("e") == BreakerState.CLOSED  # never hit 3 consecutive


def test_breaker_probe_reconcile_and_reset():
    m = ResilienceManager(ResilienceConfig(
        breaker_failure_threshold=1, breaker_open_s=60.0,
    ))
    m.record_failure("e")
    assert m.state_of("e") == BreakerState.OPEN
    # good pull-checker probe fast-forwards open -> half-open (no 60 s wait)
    m.note_probe("e", True)
    assert m.state_of("e") == BreakerState.HALF_OPEN
    # bad probe while half-open re-opens
    m.note_probe("e", False)
    assert m.state_of("e") == BreakerState.OPEN
    # offline->online recovery: fresh breaker
    m.reset("e")
    assert m.state_of("e") == BreakerState.CLOSED and m.allow("e")


def test_retry_budget_ratio_and_floor():
    b = RetryBudget(ratio=0.5, min_retries=2, window_s=60.0)
    # floor: no traffic, still 2 retries allowed
    assert b.try_spend() and b.try_spend()
    assert not b.try_spend()
    # ratio: 10 requests -> 5 allowed (floor already spent 2)
    for _ in range(10):
        b.note_request()
    assert b.allowed() == 5
    assert b.try_spend() and b.try_spend() and b.try_spend()
    assert not b.try_spend()
    snap = b.snapshot()
    assert snap["requests_in_window"] == 10
    assert snap["retries_in_window"] == 5


def test_backoff_is_capped_with_jitter():
    cfg = ResilienceConfig(backoff_base_s=0.1, backoff_cap_s=0.4)
    for attempt, hi in ((1, 0.1), (2, 0.2), (3, 0.4), (7, 0.4)):
        for _ in range(16):
            d = backoff_delay(attempt, cfg)
            assert hi / 2 <= d <= hi


def test_fault_rule_every_n_is_deterministic():
    class _Ep:
        name, id, url = "ep-a", "id-a", "http://ep-a:1"

    inj = FaultInjector()
    rule = inj.add_rule(FaultRule(kind="http", endpoint="ep-a", every_n=3))
    fired = [bool(inj.decide(_Ep(), CHAT)) for _ in range(9)]
    assert fired == [False, False, True] * 3
    assert rule.seen == 9 and rule.fires == 3
    # other endpoints don't advance the counter
    class _Other:
        name, id, url = "ep-b", "id-b", "http://ep-b:1"

    assert inj.decide(_Other(), CHAT) == []
    assert rule.seen == 9


def test_fault_rule_max_fires():
    class _Ep:
        name, id, url = "x", "x", "http://x:1"

    inj = FaultInjector()
    inj.add_rule(FaultRule(kind="connect_refused", max_fires=2))
    fires = sum(bool(inj.decide(_Ep(), CHAT)) for _ in range(5))
    assert fires == 2


# -------------------------------------------------------- chaos integration


def test_failover_nonstream_zero_client_502s():
    """Acceptance: two stubs, one model; one endpoint hard-killed via
    connect-refused injection. All non-streamed requests succeed, and the
    killed endpoint receives no further traffic after its breaker trips."""
    async def run():
        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m").start()
        dead = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            ep_dead = gw.register_mock(dead.url, ["m"], name="dead")
            manager = _set_resilience(gw, breaker_failure_threshold=3,
                                      breaker_open_s=60.0)
            gw.state.faults = FaultInjector()
            kill = gw.state.faults.add_rule(
                FaultRule(kind="connect_refused", endpoint="dead", every_n=1)
            )
            headers = await gw.inference_headers()

            n = 12
            for _ in range(n):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200, await r.text()
                await r.read()

            # zero client-visible 502s; every request ended on the live stub
            assert len(alive.requests_seen) == n
            assert len(dead.requests_seen) == 0  # fault fired pre-socket
            # breaker tripped after exactly the threshold of attempts, then
            # the dead endpoint stopped receiving traffic entirely
            assert manager.state_of(ep_dead.id) == BreakerState.OPEN
            assert kill.seen == 3
            summary = gw.state.metrics.summary()
            assert summary["failover_retries_total"] == 3
            assert summary["failover_recoveries_total"] == 3
        finally:
            await alive.stop()
            await dead.stop()
            await gw.close()
    asyncio.run(run())


def test_failover_stream_pre_first_byte():
    """Streamed requests fail over when the upstream dies before the first
    byte reaches the client — the stream arrives intact from the peer."""
    async def run():
        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m", tokens_per_reply=3).start()
        dead = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            gw.register_mock(dead.url, ["m"], name="dead")
            _set_resilience(gw, breaker_failure_threshold=3)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(
                FaultRule(kind="connect_refused", endpoint="dead", every_n=1)
            )
            headers = await gw.inference_headers()

            for _ in range(6):
                r = await gw.client.post(CHAT, json=_chat_body(stream=True),
                                         headers=headers)
                assert r.status == 200, await r.text()
                text = (await r.read()).decode()
                assert "data: [DONE]" in text
                assert "event: error" not in text
                assert_sse_protocol(text.encode(), "openai")
        finally:
            await alive.stop()
            await dead.stop()
            await gw.close()
    asyncio.run(run())


def test_midstream_cut_emits_error_frame_and_counts_outcome():
    """A stream cut after the first byte is NOT retried (bytes already left)
    but the client gets a final `event: error` frame and the interruption
    lands in the per-endpoint stats + breaker + /metrics."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m", tokens_per_reply=50).start()
        try:
            ep = gw.register_mock(mock.url, ["m"], name="cutme")
            manager = _set_resilience(gw, breaker_failure_threshold=2)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(
                FaultRule(kind="stream_cut", endpoint="cutme",
                          after_bytes=40, every_n=1)
            )
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(stream=True),
                                     headers=headers)
            assert r.status == 200  # stream had already committed
            text = (await r.read()).decode()
            assert "event: error" in text
            assert_sse_protocol(text.encode(), "openai", allow_error=True)
            frame = text.split("event: error\ndata: ")[1].split("\n")[0]
            err = json.loads(frame)["error"]
            assert err["code"] == "stream_interrupted"

            outcomes = gw.state.load_manager.endpoint_outcomes(ep.id)
            assert outcomes["stream_interruptions"] == 1
            assert manager.breaker_info(ep.id)["consecutive_failures"] == 1
            exposition = gw.state.metrics.render()
            assert ('llmlb_gateway_stream_interruptions_total'
                    '{model="m",endpoint="cutme"} 1') in exposition
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_anthropic_midstream_cut_emits_native_error_event():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m", tokens_per_reply=50).start()
        try:
            gw.register_mock(mock.url, ["m"], name="cutme")
            _set_resilience(gw)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(
                FaultRule(kind="stream_cut", endpoint="cutme",
                          after_bytes=60, every_n=1)
            )
            headers = await gw.inference_headers()
            r = await gw.client.post("/v1/messages", json={
                "model": "m", "max_tokens": 32, "stream": True,
                "messages": [{"role": "user", "content": "hi"}],
            }, headers=headers)
            assert r.status == 200
            text = (await r.read()).decode()
            assert "event: error" in text
            assert '"type":"error"' in text
            assert "message_stop" not in text.split("event: error")[1]
            assert_sse_protocol(text.encode(), "anthropic", allow_error=True)
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_retryable_5xx_fails_over():
    """A 500 from one endpoint fails over to its peer instead of
    normalizing straight to 502."""
    async def run():
        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m").start()
        broken = await MockOpenAIEndpoint(model="m", fail_with=500).start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            gw.register_mock(broken.url, ["m"], name="broken")
            _set_resilience(gw, breaker_failure_threshold=2)
            headers = await gw.inference_headers()
            for _ in range(8):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200, await r.text()
            # the 500-ing endpoint was actually contacted, then benched
            assert 1 <= len(broken.requests_seen) <= 2
        finally:
            await alive.stop()
            await broken.stop()
            await gw.close()
    asyncio.run(run())


def test_all_breakers_open_gives_503_queue_semantics_not_404():
    """Satellite: endpoints exist but every breaker is open -> the request
    queues and 503s with Retry-After derived from the breaker, never 404."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m").start()
        try:
            ep = gw.register_mock(mock.url, ["m"], name="only")
            manager = _set_resilience(gw, breaker_failure_threshold=1,
                                      breaker_open_s=7.0)
            # short queue timeout so the park resolves quickly
            gw.state.load_manager.queue_config = QueueConfig(
                queue_timeout_s=0.2)
            manager.record_failure(ep.id)
            assert manager.state_of(ep.id) == BreakerState.OPEN

            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 503, await r.text()
            retry_after = int(r.headers["Retry-After"])
            assert 1 <= retry_after <= 7
            body = await r.json()
            assert body["error"]["type"] == "server_error"
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_unknown_model_still_404s():
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(mock.url, ["m"])
            _set_resilience(gw)
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(model="absent"),
                                     headers=headers)
            assert r.status == 404
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_retry_budget_stops_amplification():
    """With the budget floor at zero and no recent traffic, a failing fleet
    gets no retries at all — the 502 is immediate, not amplified."""
    async def run():
        gw = await GatewayHarness.create()
        a = await MockOpenAIEndpoint(model="m", fail_with=500).start()
        b = await MockOpenAIEndpoint(model="m", fail_with=500).start()
        try:
            gw.register_mock(a.url, ["m"], name="a")
            gw.register_mock(b.url, ["m"], name="b")
            _set_resilience(gw, retry_budget_min=0, retry_budget_ratio=0.0,
                            breaker_failure_threshold=100)
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 502
            # exactly one upstream attempt total: no budget, no retry
            assert len(a.requests_seen) + len(b.requests_seen) == 1
            exposition = gw.state.metrics.render()
            assert "llmlb_gateway_retry_budget_exhausted_total 1" in exposition
        finally:
            await a.stop()
            await b.stop()
            await gw.close()
    asyncio.run(run())


def test_body_read_failure_fails_over():
    """Regression: an endpoint that returns 200 headers then dies mid-body
    (truncated read) must fail over like a connect failure — and book the
    outcome, not crash the handler to a raw 500."""
    async def run():
        from aiohttp import web
        from aiohttp.test_utils import TestServer

        async def broken_chat(request):
            await request.read()
            resp = web.StreamResponse(status=200, headers={
                "Content-Type": "application/json",
                "Content-Length": "1000",  # promises more than it sends
            })
            await resp.prepare(request)
            await resp.write(b'{"partial":')
            request.transport.close()
            return resp

        app = web.Application()
        app.router.add_post("/v1/chat/completions", broken_chat)
        broken = TestServer(app)
        await broken.start_server()

        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            ep_broken = gw.register_mock(
                f"http://127.0.0.1:{broken.port}", ["m"], name="broken")
            manager = _set_resilience(gw, breaker_failure_threshold=2)
            headers = await gw.inference_headers()
            for _ in range(6):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200, await r.text()
            assert (gw.state.load_manager.endpoint_outcomes(ep_broken.id)
                    ["failures"]) >= 1
            assert manager.state_of(ep_broken.id) == BreakerState.OPEN
        finally:
            await alive.stop()
            await broken.close()
            await gw.close()
    asyncio.run(run())


def test_429_fails_over_but_does_not_feed_breaker():
    """A saturated endpoint (429) is alive: its requests fail over, but
    ejecting it would turn an overload spike into a capacity cascade, so
    the breaker must not move."""
    async def run():
        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m").start()
        busy = await MockOpenAIEndpoint(model="m", fail_with=429).start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            ep_busy = gw.register_mock(busy.url, ["m"], name="busy")
            manager = _set_resilience(gw, breaker_failure_threshold=2)
            headers = await gw.inference_headers()
            for _ in range(8):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200, await r.text()
            # failover happened, breaker did not trip
            assert len(busy.requests_seen) >= 2
            assert manager.state_of(ep_busy.id) == BreakerState.CLOSED
        finally:
            await alive.stop()
            await busy.stop()
            await gw.close()
    asyncio.run(run())


def test_deleting_endpoint_clears_breaker_gauge():
    """Regression: an endpoint removed while its breaker is open must not
    keep exporting llmlb_gateway_breaker_state (a frozen open reading
    would page on a nonexistent endpoint forever)."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m").start()
        try:
            ep = gw.register_mock(mock.url, ["m"], name="doomed")
            manager = _set_resilience(gw, breaker_failure_threshold=1)
            manager.record_failure(ep.id)
            assert ('llmlb_gateway_breaker_state{endpoint="doomed"} 2'
                    in gw.state.metrics.render())
            admin = await gw.admin_headers()
            r = await gw.client.delete(f"/api/endpoints/{ep.id}",
                                       headers=admin)
            assert r.status == 200
            assert ('llmlb_gateway_breaker_state{endpoint="doomed"}'
                    not in gw.state.metrics.render())
            assert manager.state_of(ep.id) == BreakerState.CLOSED
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_half_open_probe_resolved_by_non_retryable_response():
    """Regression: a half-open probe answered with a non-retryable 4xx must
    resolve the probe slot (endpoint is alive) instead of wedging the
    breaker in half_open with its only slot consumed forever."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m", fail_with=400).start()
        try:
            ep = gw.register_mock(mock.url, ["m"], name="flaky")
            manager = _set_resilience(gw, breaker_failure_threshold=1,
                                      breaker_open_s=0.05)
            manager.record_failure(ep.id)
            assert manager.state_of(ep.id) == BreakerState.OPEN
            await asyncio.sleep(0.06)  # open interval elapses

            headers = await gw.inference_headers()
            # probe request: upstream answers 400 (non-retryable) -> client
            # sees the normalized 502, breaker records liveness and closes
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 502
            assert manager.state_of(ep.id) == BreakerState.CLOSED
            # NOT wedged: the endpoint still receives traffic
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 502
            assert len(mock.requests_seen) == 2
        finally:
            await mock.stop()
            await gw.close()
    asyncio.run(run())


def test_flap_cycle_trips_then_recovers_through_half_open():
    """Chaos smoke: endpoint dies (trip), comes back (half-open probe
    succeeds), and rejoins the serving pool — all via in-band signals, no
    pull-checker involvement, zero client-visible errors throughout."""
    async def run():
        gw = await GatewayHarness.create()
        stable = await MockOpenAIEndpoint(model="m").start()
        flappy = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(stable.url, ["m"], name="stable")
            ep_flap = gw.register_mock(flappy.url, ["m"], name="flappy")
            manager = _set_resilience(gw, breaker_failure_threshold=2,
                                      breaker_open_s=0.1)
            gw.state.faults = FaultInjector()
            rule = gw.state.faults.add_rule(
                FaultRule(kind="connect_refused", endpoint="flappy",
                          every_n=1)
            )
            headers = await gw.inference_headers()

            async def burst(n):
                for _ in range(n):
                    r = await gw.client.post(CHAT, json=_chat_body(),
                                             headers=headers)
                    assert r.status == 200, await r.text()

            await burst(6)  # down phase: trips after 2 in-band failures
            assert manager.state_of(ep_flap.id) == BreakerState.OPEN

            gw.state.faults.remove_rule(rule)  # endpoint comes back
            await asyncio.sleep(0.12)  # open interval elapses
            await burst(6)  # half-open probe succeeds -> closed + serving
            assert manager.state_of(ep_flap.id) == BreakerState.CLOSED
            assert len(flappy.requests_seen) >= 1
        finally:
            await stable.stop()
            await flappy.stop()
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------- breaker <-> pull checker


def test_health_probe_reconciles_breaker_and_recovery_resyncs_models():
    """Satellite: offline->online re-detection + model resync, and the
    breaker reconciling with the pull checker in both directions."""
    async def run():
        gw = await GatewayHarness.create()
        mock = await MockOpenAIEndpoint(model="m1").start()
        try:
            ep = gw.register_mock(mock.url, ["m1"], name="flappy")
            manager = _set_resilience(gw, breaker_failure_threshold=1,
                                      breaker_open_s=3600.0)
            checker = EndpointHealthChecker(
                gw.state.registry, gw.state.load_manager, gw.state.db,
                gw.state.http, gw.state.events, interval_s=3600.0,
                timeout_s=2.0, resilience=manager,
            )
            # in-band trip; the endpoint is still ONLINE per the registry
            manager.record_failure(ep.id)
            assert manager.state_of(ep.id) == BreakerState.OPEN
            assert gw.state.registry.get(ep.id).breaker_state == "open"

            # good pull probe fast-forwards the breaker to half-open
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert manager.state_of(ep.id) == BreakerState.HALF_OPEN
            assert gw.state.registry.get(ep.id).breaker_state == "half_open"

            # the next real request is the probe; success closes the breaker
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body("m1"),
                                     headers=headers)
            assert r.status == 200
            assert manager.state_of(ep.id) == BreakerState.CLOSED

            # now kill it for the pull checker: two strikes -> OFFLINE
            manager.record_failure(ep.id)
            port = mock.server.port
            await mock.stop()
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            await checker.check_endpoint(gw.state.registry.get(ep.id))
            assert (gw.state.registry.get(ep.id).status
                    == EndpointStatus.OFFLINE)

            # recovery on the same port with a NEW model set: back online,
            # models resynced, breaker reset to closed
            from aiohttp import web
            from aiohttp.test_utils import TestServer as TS
            mock2 = MockOpenAIEndpoint(model="m2")
            app = web.Application()
            app.router.add_get("/v1/models", mock2._models)
            mock2.server = TS(app, port=port)
            await mock2.server.start_server()
            try:
                await checker.check_endpoint(gw.state.registry.get(ep.id))
                ep_after = gw.state.registry.get(ep.id)
                assert ep_after.status == EndpointStatus.ONLINE
                models = [m.model_id
                          for m in gw.state.registry.models_for(ep.id)]
                assert models == ["m2"]
                assert manager.state_of(ep.id) == BreakerState.CLOSED
                assert ep_after.breaker_state == "closed"
            finally:
                await mock2.server.close()
        finally:
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------------------ observability


def test_api_health_and_metrics_surfaces():
    """Breaker state + retry/failover counters visible in /api/health and
    /metrics (acceptance), and /api/health needs no auth."""
    async def run():
        gw = await GatewayHarness.create()
        alive = await MockOpenAIEndpoint(model="m").start()
        dead = await MockOpenAIEndpoint(model="m").start()
        try:
            gw.register_mock(alive.url, ["m"], name="alive")
            ep_dead = gw.register_mock(dead.url, ["m"], name="dead")
            _set_resilience(gw, breaker_failure_threshold=2,
                            breaker_open_s=60.0)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(
                FaultRule(kind="connect_refused", endpoint="dead", every_n=1)
            )
            headers = await gw.inference_headers()
            for _ in range(6):
                r = await gw.client.post(CHAT, json=_chat_body(),
                                         headers=headers)
                assert r.status == 200

            r = await gw.client.get("/api/health")  # unauthenticated
            assert r.status == 200
            health = await r.json()
            by_name = {e["name"]: e for e in health["endpoints"]}
            assert by_name["dead"]["breaker"]["state"] == "open"
            assert by_name["dead"]["breaker"]["retry_after_s"] > 0
            assert by_name["alive"]["breaker"]["state"] == "closed"
            assert by_name["alive"]["outcomes"]["successes"] >= 1
            assert health["endpoints_serving"] == 1
            assert health["resilience"]["retry_budget"]["requests_in_window"] >= 6
            assert health["faults"][0]["fires"] == 2

            r = await gw.client.get("/metrics")
            text = await r.text()
            assert 'llmlb_gateway_breaker_state{endpoint="dead"} 2' in text
            assert ('llmlb_gateway_breaker_transitions_total'
                    '{endpoint="dead",to="open"} 1') in text
            assert ('llmlb_gateway_failover_retries_total'
                    '{model="m",reason="connect_error"} 2') in text
            assert ('llmlb_gateway_failover_recoveries_total'
                    '{model="m"} 2') in text
            assert ('llmlb_gateway_faults_injected_total'
                    '{kind="connect_refused"} 2') in text

            # /api/endpoints carries the breaker state too
            admin = await gw.admin_headers()
            r = await gw.client.get("/api/endpoints", headers=admin)
            eps = (await r.json())["endpoints"]
            states = {e["name"]: e["breaker_state"] for e in eps}
            assert states == {"alive": "closed", "dead": "open"}
            assert gw.state.registry.get(ep_dead.id).breaker_state == "open"
        finally:
            await alive.stop()
            await dead.stop()
            await gw.close()
    asyncio.run(run())


def test_queue_timeout_503_carries_retry_after():
    """Satellite: plain capacity 503 (no breakers involved) also carries a
    Retry-After derived from the queue config."""
    async def run():
        gw = await GatewayHarness.create()
        slow = await MockOpenAIEndpoint(model="m", reply_delay_s=1.0).start()
        try:
            gw.register_mock(slow.url, ["m"], name="slow")
            _set_resilience(gw)
            gw.state.load_manager.queue_config = QueueConfig(
                queue_timeout_s=0.15, max_active_per_endpoint=1)
            headers = await gw.inference_headers()
            blocker = asyncio.create_task(gw.client.post(
                CHAT, json=_chat_body(), headers=headers))
            await asyncio.sleep(0.05)  # let it occupy the only slot
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 503
            assert int(r.headers["Retry-After"]) >= 1
            resp = await blocker
            assert resp.status == 200
        finally:
            await slow.stop()
            await gw.close()
    asyncio.run(run())
