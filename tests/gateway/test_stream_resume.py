"""Durable streams: mid-stream engine failover via token-identical replay.

All scenarios run in-process and deterministically: the `engine_abort` fault
rule (gateway/faults.py) reproduces a SIGKILLed engine at the proxy's HTTP
boundary — connection reset after K delivered bytes, no partial event, no
prior error frame — and MockResumableEndpoint plays the engine side of the
/v1/resume contract (llmlb.replay frames + full-text adopt replay). Tier-1.
The real-process SIGKILL drill lives in test_chaos_engine_kill.py and
`bench_gateway.py --workload chaos --engine-kill`.
"""

import asyncio
import json
import os

from llmlb_tpu.gateway.config import ResilienceConfig
from llmlb_tpu.gateway.faults import FaultInjector, FaultRule
from llmlb_tpu.gateway.resilience import BreakerState, ResilienceManager
from llmlb_tpu.gateway.types import EndpointType
from tests.support import (
    GatewayHarness,
    MockResumableEndpoint,
    assert_sse_protocol,
)

CHAT = "/v1/chat/completions"
MESSAGES = "/v1/messages"

SCRIPT = list(range(100, 112))  # the tokens every "engine" generates
FULL_TEXT = "".join(MockResumableEndpoint.text_of(t) for t in SCRIPT)


def _chat_body(stream=True):
    return {"model": "m", "stream": stream,
            "messages": [{"role": "user", "content": "ping"}]}


def _messages_body():
    return {"model": "m", "stream": True, "max_tokens": 32,
            "messages": [{"role": "user", "content": "ping"}]}


def _set_resilience(gw, **overrides) -> ResilienceManager:
    cfg = ResilienceConfig(**{
        "backoff_base_s": 0.001, "backoff_cap_s": 0.002,
        "failover_queue_timeout_s": 0.3, **overrides,
    })
    manager = ResilienceManager(
        cfg, metrics=gw.state.metrics, events=gw.state.events,
        registry=gw.state.registry,
    )
    gw.state.resilience = manager
    gw.state.load_manager.resilience = manager
    return manager


def _openai_stream_text(body: bytes) -> str:
    """Concatenated delta content of an OpenAI chat SSE body."""
    parts = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        data = line[len(b"data:"):].strip()
        if not data or data == b"[DONE]":
            continue
        try:
            obj = json.loads(data)
        except ValueError:
            continue
        for choice in obj.get("choices") or []:
            content = (choice.get("delta") or {}).get("content")
            if isinstance(content, str):
                parts.append(content)
    return "".join(parts)


def _anthropic_stream_text(body: bytes) -> str:
    parts = []
    for line in body.split(b"\n"):
        line = line.strip()
        if not line.startswith(b"data:"):
            continue
        try:
            obj = json.loads(line[len(b"data:"):].strip())
        except ValueError:
            continue
        if obj.get("type") == "content_block_delta":
            delta = obj.get("delta") or {}
            if delta.get("type") == "text_delta":
                parts.append(delta.get("text", ""))
    return "".join(parts)


async def _resume_pair(gw):
    """Two resumable tpu:// mocks serving one model, resilience wired."""
    a = await MockResumableEndpoint(model="m", script=SCRIPT).start()
    b = await MockResumableEndpoint(model="m", script=SCRIPT).start()
    ep_a = gw.register_mock(a.url, ["m"], endpoint_type=EndpointType.TPU,
                            name="eng-a")
    ep_b = gw.register_mock(b.url, ["m"], endpoint_type=EndpointType.TPU,
                            name="eng-b")
    manager = _set_resilience(gw, breaker_failure_threshold=3)
    gw.state.faults = FaultInjector()
    return a, b, ep_a, ep_b, manager


# ------------------------------------------------------------ OpenAI dialect


def test_openai_midstream_resume_token_identical():
    """An engine_abort mid-stream splices a token-identical continuation
    from the other engine into the SAME response: full text, one role
    delta, exactly one [DONE], no error frame, no replay-frame leak."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _resume_pair(gw)
            # kill whichever engine serves the first stream after ~4 tokens
            # (role frame + a few replay/content frame pairs)
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200, await r.text()
            body = await r.read()
            assert b"event: error" not in body
            assert_sse_protocol(body, "openai")
            assert _openai_stream_text(body) == FULL_TEXT
            # exactly one resume happened, with a non-empty committed replay
            resumes = a.resume_calls + b.resume_calls
            assert len(resumes) == 1
            committed = resumes[0]["committed_ids"]
            assert committed == SCRIPT[:len(committed)]
            assert len(committed) > 0
            summary = gw.state.metrics.summary()
            assert summary["stream_resumes"] == {"success": 1}
            assert summary["stream_resumed_tokens_total"] == len(committed)
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_resume_accounting_victim_and_resumer():
    """Satellite: the dead endpoint records exactly one stream_interruption
    + one breaker failure; the resuming endpoint records a clean success;
    the victim is excluded from resume selection (never burns a probe)."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _resume_pair(gw)
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200
            await r.read()

            victim_mock, resumer_mock = (a, b) if a.resume_calls == [] else (b, a)
            # identify the victim endpoint record by which mock got /v1/resume
            victim_ep = ep_a if resumer_mock is b else ep_b
            resumer_ep = ep_b if victim_ep is ep_a else ep_a
            assert len(resumer_mock.resume_calls) == 1
            assert victim_mock.resume_calls == []

            outcomes = gw.state.load_manager.endpoint_outcomes()
            vo = outcomes[victim_ep.id]
            assert vo["stream_interruptions"] == 1
            assert vo["failures"] == 1
            ro = outcomes[resumer_ep.id]
            assert ro["successes"] == 1
            assert ro.get("stream_interruptions", 0) == 0
            # exactly one breaker failure on the victim, none on the resumer
            assert (manager.breaker_info(victim_ep.id)
                    ["consecutive_failures"]) == 1
            assert manager.state_of(resumer_ep.id) == BreakerState.CLOSED
            summary = gw.state.metrics.summary()
            assert summary["stream_interruptions_total"] == 1
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


def test_resume_giveup_emits_single_error_frame():
    """With no surviving endpoint to resume on, the cut stays terminal: one
    error frame, no duplicate interruption accounting, outcome counted."""
    async def run():
        gw = await GatewayHarness.create()
        a = None
        try:
            a = await MockResumableEndpoint(model="m", script=SCRIPT).start()
            ep_a = gw.register_mock(a.url, ["m"],
                                    endpoint_type=EndpointType.TPU,
                                    name="only")
            manager = _set_resilience(gw, breaker_failure_threshold=3)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200
            body = await r.read()
            assert body.count(b"event: error") == 1
            assert_sse_protocol(body, "openai", allow_error=True)
            # partial text only — a prefix of the full run, never garbage
            text = _openai_stream_text(body)
            assert FULL_TEXT.startswith(text) and text != FULL_TEXT
            outcomes = gw.state.load_manager.endpoint_outcomes()[ep_a.id]
            assert outcomes["stream_interruptions"] == 1
            assert outcomes["failures"] == 1
            summary = gw.state.metrics.summary()
            assert summary["stream_resumes"] == {"no_endpoint": 1}
        finally:
            if a is not None:
                await a.stop()
            await gw.close()
    asyncio.run(run())


def test_double_cut_resumes_twice():
    """A resumed stream that is cut AGAIN resumes again (attempts cap 2):
    the committed ledger rebuilt from the adopter's replay frames covers
    the second splice too."""
    async def run():
        gw = await GatewayHarness.create()
        mocks = []
        try:
            for i in range(3):
                mocks.append(await MockResumableEndpoint(
                    model="m", script=SCRIPT).start())
            for i, m in enumerate(mocks):
                gw.register_mock(m.url, ["m"],
                                 endpoint_type=EndpointType.TPU,
                                 name=f"eng-{i}")
            _set_resilience(gw, breaker_failure_threshold=5)
            gw.state.faults = FaultInjector()
            # first cut on the primary stream, second on the resumed one
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="resume",
                after_bytes=1200, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200
            body = await r.read()
            assert b"event: error" not in body
            assert_sse_protocol(body, "openai")
            assert _openai_stream_text(body) == FULL_TEXT
            assert sum(len(m.resume_calls) for m in mocks) == 2
            summary = gw.state.metrics.summary()
            assert summary["stream_resumes"] == {"success": 2}
        finally:
            for m in mocks:
                await m.stop()
            await gw.close()
    asyncio.run(run())


def test_resume_disabled_keeps_terminal_error_frame():
    """LLMLB_STREAM_RESUME=0 restores the PR 4 contract: a mid-stream cut
    is terminal and emits the error frame."""
    async def run():
        os.environ["LLMLB_STREAM_RESUME"] = "0"
        try:
            gw = await GatewayHarness.create()
        finally:
            del os.environ["LLMLB_STREAM_RESUME"]
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _resume_pair(gw)
            assert gw.state.config.stream_resume is False
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            assert r.status == 200
            body = await r.read()
            assert body.count(b"event: error") == 1
            assert a.resume_calls == [] and b.resume_calls == []
            # unarmed: the engines were never asked for replay frames
            assert not any(req.get("llmlb_replay")
                           for req in a.requests_seen + b.requests_seen)
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


# --------------------------------------------------------- Anthropic dialect


def test_anthropic_midstream_resume_single_message():
    """The Anthropic transform resumes through the SAME stateful encoder:
    full text, exactly one message_start and one message_stop, monotone
    block indices."""
    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a, b, ep_a, ep_b, manager = await _resume_pair(gw)
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(MESSAGES, json=_messages_body(),
                                     headers=headers)
            assert r.status == 200, await r.text()
            body = await r.read()
            assert b'"type":"error"' not in body.replace(b" ", b"")
            assert_sse_protocol(body, "anthropic")
            assert _anthropic_stream_text(body) == FULL_TEXT
            assert len(a.resume_calls + b.resume_calls) == 1
            summary = gw.state.metrics.summary()
            assert summary["stream_resumes"] == {"success": 1}
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())


# ------------------------------------------------------- engine_abort rule


def test_engine_abort_distinct_from_stream_cut():
    """engine_abort resets the connection BETWEEN frames (no partial event,
    no prior error frame): the client-visible prefix is always well-formed
    whole frames — unlike stream_cut, which may truncate mid-line."""
    async def run():
        gw = await GatewayHarness.create()
        a = None
        try:
            a = await MockResumableEndpoint(model="m", script=SCRIPT).start()
            gw.register_mock(a.url, ["m"], endpoint_type=EndpointType.TPU,
                             name="only")
            _set_resilience(gw)
            gw.state.faults = FaultInjector()
            # the resume pump forwards whole frames only, so prove the rule
            # itself yields whole chunks: abort lands between resp.write()s
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            r = await gw.client.post(CHAT, json=_chat_body(),
                                     headers=headers)
            body = await r.read()
            # every forwarded frame parses: nothing was truncated mid-line
            assert_sse_protocol(body, "openai", allow_error=True)
        finally:
            if a is not None:
                await a.stop()
            await gw.close()
    asyncio.run(run())


def test_midstream_resume_with_active_adapter_token_identical():
    """Satellite (docs/lora.md): a stream with a LoRA adapter attached cuts
    mid-generation and resumes token-identically — the resume POST carries
    the SAME `lora` field (it rides the original chat body), so the adopting
    engine replays prompt+committed through the same adapter deltas."""
    from llmlb_tpu.gateway.types import Capability

    async def run():
        gw = await GatewayHarness.create()
        a = b = None
        try:
            a = await MockResumableEndpoint(model="m", script=SCRIPT).start()
            b = await MockResumableEndpoint(model="m", script=SCRIPT).start()
            caps = [Capability.CHAT_COMPLETION, Capability.LORA]
            for mock, name in ((a, "eng-a"), (b, "eng-b")):
                gw.register_mock(mock.url, ["m"],
                                 endpoint_type=EndpointType.TPU,
                                 capabilities=caps, name=name)
            _set_resilience(gw, breaker_failure_threshold=3)
            gw.state.faults = FaultInjector()
            gw.state.faults.add_rule(FaultRule(
                kind="engine_abort", endpoint="*", path="chat",
                after_bytes=900, max_fires=1,
            ))
            headers = await gw.inference_headers()
            body = {**_chat_body(), "lora": "acme"}
            r = await gw.client.post(CHAT, json=body, headers=headers)
            assert r.status == 200, await r.text()
            raw = await r.read()
            assert b"event: error" not in raw
            assert_sse_protocol(raw, "openai")
            assert _openai_stream_text(raw) == FULL_TEXT
            # the first engine saw the adapter (cold-load route: model
            # suffix + explicit field, agreeing)
            first = (a.requests_seen + b.requests_seen)[0]
            assert first["lora"] == "acme"
            assert first["model"] == "m:acme"
            # exactly one resume, and its body still names the adapter
            resumes = a.resume_calls + b.resume_calls
            assert len(resumes) == 1
            assert resumes[0]["lora"] == "acme"
            committed = resumes[0]["committed_ids"]
            assert committed == SCRIPT[:len(committed)] and committed
            assert gw.state.metrics.summary()["stream_resumes"] == {
                "success": 1
            }
        finally:
            for m in (a, b):
                if m is not None:
                    await m.stop()
            await gw.close()
    asyncio.run(run())
