"""Gateway-level structured outputs: a live gateway + real tpu:// engine
(CPU JAX). Covers the acceptance path end to end — `response_format:
json_schema` streamed through /v1/chat/completions parses and validates
with finish_reason "stop" at grammar acceptance; forced tool_choice works
on both the OpenAI and Anthropic dialects; malformed/unsupported requests
400 in each dialect's error shape; capability routing steers constrained
traffic to structured-capable endpoints."""

import asyncio
import json

import jsonschema
import pytest
from aiohttp.test_utils import TestServer

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.types import Capability
from tests.support import (
    GatewayHarness,
    MockOpenAIEndpoint,
    assert_sse_protocol,
)

SCHEMA = {
    "type": "object",
    "properties": {
        "city": {"enum": ["sf", "nyc"]},
        "celsius": {"type": "boolean"},
    },
    "required": ["city", "celsius"],
}


@pytest.fixture(scope="module")
def engine():
    eng = Engine.from_preset(
        "debug-tiny", model_id="tpu-structured", num_slots=4,
        slot_capacity=128, prefill_buckets=(16, 32, 64),
    )
    yield eng
    eng.shutdown()


def test_structured_outputs_through_gateway(engine):
    async def run():
        gw = await GatewayHarness.create()
        engine_server = TestServer(create_engine_app(engine,
                                                     owns_engine=False))
        await engine_server.start_server()
        gw.state.health_checker = EndpointHealthChecker(
            gw.state.registry, gw.state.load_manager, gw.state.db,
            gw.state.http, gw.state.events, interval_s=3600, timeout_s=5.0,
        )
        try:
            headers = await gw.admin_headers()
            r = await gw.client.post("/api/endpoints", json={
                "base_url": f"http://127.0.0.1:{engine_server.port}",
                "name": "tpu0"}, headers=headers)
            assert r.status == 201, await r.text()
            ep_id = (await r.json())["id"]

            # model sync picked up the structured_outputs capability advert
            caps = {
                c for m in gw.state.registry.models_for(ep_id)
                for c in m.capabilities
            }
            assert Capability.STRUCTURED_OUTPUTS in caps, caps

            iheaders = await gw.inference_headers()

            # --- streamed json_schema through the gateway (acceptance) ---
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-structured", "max_tokens": 64,
                "temperature": 1.0, "stream": True, "seed": 11,
                "messages": [{"role": "user", "content": "weather json"}],
                "response_format": {"type": "json_schema", "json_schema": {
                    "name": "weather", "schema": SCHEMA}},
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            raw = (await r.read()).decode()
            assert_sse_protocol(raw.encode(), "openai")
            text, finish = "", None
            for line in raw.splitlines():
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                chunk = json.loads(line[6:])
                for choice in chunk.get("choices", []):
                    delta = choice.get("delta", {})
                    if delta.get("content"):
                        text += delta["content"]
                    if choice.get("finish_reason"):
                        finish = choice["finish_reason"]
            assert finish == "stop", raw[:400]
            jsonschema.validate(json.loads(text), SCHEMA)

            # --- forced tool call, OpenAI dialect, non-streamed ---
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-structured", "max_tokens": 64,
                "temperature": 0.0,
                "messages": [{"role": "user", "content": "call the tool"}],
                "tools": [{"type": "function", "function": {
                    "name": "get_weather", "parameters": SCHEMA}}],
                "tool_choice": {"type": "function",
                                "function": {"name": "get_weather"}},
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            choice = (await r.json())["choices"][0]
            assert choice["finish_reason"] == "tool_calls"
            call = choice["message"]["tool_calls"][0]
            assert call["function"]["name"] == "get_weather"
            jsonschema.validate(json.loads(call["function"]["arguments"]),
                                SCHEMA)

            # --- forced tool call, Anthropic dialect ---
            r = await gw.client.post("/v1/messages", json={
                "model": "tpu-structured", "max_tokens": 64,
                "messages": [{"role": "user", "content": "call the tool"}],
                "tools": [{"name": "get_weather",
                           "input_schema": SCHEMA}],
                "tool_choice": {"type": "tool", "name": "get_weather"},
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            msg = await r.json()
            blocks = [b for b in msg["content"] if b["type"] == "tool_use"]
            assert blocks and blocks[0]["name"] == "get_weather"
            jsonschema.validate(blocks[0]["input"], SCHEMA)
            assert msg["stop_reason"] == "tool_use"

            # --- gateway-side validation: unsupported feature named, 400 ---
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-structured",
                "messages": [{"role": "user", "content": "x"}],
                "response_format": {"type": "json_schema", "json_schema": {
                    "name": "bad",
                    "schema": {"type": "object",
                               "patternProperties": {"": {}}}}},
            }, headers=iheaders)
            assert r.status == 400
            err = await r.json()
            assert err["error"]["type"] == "invalid_request_error"
            assert "patternProperties" in err["error"]["message"]

            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-structured",
                "messages": [{"role": "user", "content": "x"}],
                "response_format": {"type": "yaml"},
            }, headers=iheaders)
            assert r.status == 400
            assert "yaml" in (await r.json())["error"]["message"]

            # malformed tool_choice on the Anthropic dialect: its error shape
            r = await gw.client.post("/v1/messages", json={
                "model": "tpu-structured", "max_tokens": 16,
                "messages": [{"role": "user", "content": "x"}],
                "tools": [{"name": "t",
                           "input_schema": {"type": "object",
                                            "allOf": []}}],
                "tool_choice": {"type": "tool", "name": "t"},
            }, headers=iheaders)
            assert r.status == 400
            body = await r.json()
            assert body["type"] == "error"
            assert "allOf" in body["error"]["message"]

            # the engine never saw the rejected requests; gateway counters did
            scrape = await (await gw.client.get("/metrics")).text()
            assert "llmlb_gateway_structured_requests_total" in scrape
            assert "llmlb_gateway_structured_rejected_total 3" in scrape
        finally:
            await engine_server.close()
            await gw.close()
    asyncio.run(run())


def test_constrained_requests_steered_to_capable_endpoint(engine):
    """Same model on two endpoints — one mock without structured_outputs,
    the real engine with it. Constrained requests must always land on the
    engine; the capability-blind mock must see none of them."""
    async def run():
        gw = await GatewayHarness.create()
        engine_server = TestServer(create_engine_app(engine,
                                                     owns_engine=False))
        await engine_server.start_server()
        mock = await MockOpenAIEndpoint(model="tpu-structured").start()
        try:
            gw.register_mock(mock.url, ["tpu-structured"], name="blind")
            gw.register_mock(
                f"http://127.0.0.1:{engine_server.port}",
                ["tpu-structured"], name="tpu0",
                capabilities=[Capability.CHAT_COMPLETION,
                              Capability.STRUCTURED_OUTPUTS],
            )
            iheaders = await gw.inference_headers()
            for i in range(4):
                r = await gw.client.post("/v1/chat/completions", json={
                    "model": "tpu-structured", "max_tokens": 48,
                    "temperature": 0.0,
                    "messages": [{"role": "user", "content": f"req {i}"}],
                    "response_format": {"type": "json_schema",
                                        "json_schema": {"name": "w",
                                                        "schema": SCHEMA}},
                }, headers=iheaders)
                assert r.status == 200, await r.text()
                body = await r.json()
                jsonschema.validate(
                    json.loads(body["choices"][0]["message"]["content"]),
                    SCHEMA,
                )
            assert not mock.requests_seen, (
                "constrained request reached a non-structured endpoint"
            )
            # free-form traffic still spreads over both endpoints eventually
            for i in range(8):
                r = await gw.client.post("/v1/chat/completions", json={
                    "model": "tpu-structured", "max_tokens": 4,
                    "messages": [{"role": "user", "content": f"free {i}"}],
                }, headers=iheaders)
                assert r.status == 200
        finally:
            await mock.stop()
            await engine_server.close()
            await gw.close()
    asyncio.run(run())
