"""Per-endpoint device probes (reference system_info/mod.rs dispatch,
llamacpp.rs /slots + /metrics strategies) surfaced at
GET /api/endpoints/{id}/system-info."""

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from llmlb_tpu.gateway.types import EndpointType


class MockLlamaCpp:
    def __init__(self, with_slots=True):
        self.with_slots = with_slots
        self.server: TestServer | None = None

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self):
        app = web.Application()
        if self.with_slots:
            app.router.add_get("/slots", self._slots)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/v1/models", self._models)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def stop(self):
        await self.server.close()

    async def _slots(self, request):
        return web.json_response([
            {"id": 0, "n_ctx": 8192, "is_processing": True},
            {"id": 1, "n_ctx": 8192, "is_processing": False},
        ])

    async def _metrics(self, request):
        return web.Response(
            text="llamacpp:kv_cache_tokens 1234\nother 1\n",
            content_type="text/plain",
        )

    async def _models(self, request):
        return web.json_response({"data": [{"id": "m"}]})


class MockOllamaRuntime:
    def __init__(self):
        self.server: TestServer | None = None

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self):
        app = web.Application()
        app.router.add_get("/api/version", lambda r: web.json_response(
            {"version": "0.5.1"}))
        app.router.add_get("/api/ps", lambda r: web.json_response({
            "models": [
                {"name": "llama3:8b", "size_vram": 5_000_000_000},
                {"name": "qwen2.5:0.5b", "size_vram": 500_000_000},
            ],
        }))
        app.router.add_get("/v1/models", lambda r: web.json_response(
            {"data": [{"id": "llama3:8b"}]}))
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def stop(self):
        await self.server.close()


@pytest.mark.asyncio
async def test_llama_cpp_slots_probe():
    from tests.support import GatewayHarness

    gw = await GatewayHarness.create()
    mock = await MockLlamaCpp().start()
    try:
        gw.register_mock(mock.url, ["m"], endpoint_type=EndpointType.LLAMA_CPP)
        eid = gw.state.registry.list_all()[0].id
        headers = await gw.admin_headers()
        resp = await gw.client.get(
            f"/api/endpoints/{eid}/system-info", headers=headers
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["available"] is True
        assert body["info"]["device"] == "llama.cpp"
        assert body["info"]["parallel_slots"] == 2
        assert body["info"]["n_ctx"] == 8192
        assert body["info"]["busy_slots"] == 1
        assert body["info"]["source"] == "slots"
    finally:
        await mock.stop()
        await gw.close()


@pytest.mark.asyncio
async def test_llama_cpp_metrics_fallback():
    from tests.support import GatewayHarness

    gw = await GatewayHarness.create()
    mock = await MockLlamaCpp(with_slots=False).start()
    try:
        gw.register_mock(mock.url, ["m"], endpoint_type=EndpointType.LLAMA_CPP)
        eid = gw.state.registry.list_all()[0].id
        headers = await gw.admin_headers()
        body = await (await gw.client.get(
            f"/api/endpoints/{eid}/system-info", headers=headers
        )).json()
        assert body["info"]["source"] == "metrics"
        assert body["info"]["kv_cache_tokens"] == 1234
    finally:
        await mock.stop()
        await gw.close()


@pytest.mark.asyncio
async def test_ollama_probe_and_unsupported_type():
    from tests.support import GatewayHarness

    gw = await GatewayHarness.create()
    mock = await MockOllamaRuntime().start()
    try:
        gw.register_mock(
            mock.url, ["llama3:8b"], endpoint_type=EndpointType.OLLAMA
        )
        gw.register_mock(
            "http://127.0.0.1:9", ["x"],
            endpoint_type=EndpointType.OPENAI_COMPATIBLE,
        )
        headers = await gw.admin_headers()
        eps = {e.endpoint_type: e for e in gw.state.registry.list_all()}

        body = await (await gw.client.get(
            f"/api/endpoints/{eps[EndpointType.OLLAMA].id}/system-info",
            headers=headers,
        )).json()
        assert body["info"]["version"] == "0.5.1"
        assert body["info"]["loaded_models"] == ["llama3:8b", "qwen2.5:0.5b"]
        assert body["info"]["vram_bytes"] == 5_500_000_000

        # generic OpenAI-compatible runtimes expose nothing probeable
        body = await (await gw.client.get(
            f"/api/endpoints/{eps[EndpointType.OPENAI_COMPATIBLE].id}"
            "/system-info",
            headers=headers,
        )).json()
        assert body["available"] is False and body["info"] is None
    finally:
        await mock.stop()
        await gw.close()
