"""Cross-process timeline join + multi-worker trace spool (docs/tracing.md).

`/api/traces/{id}?view=timeline` merges the gateway's own spans with the
flight-recorder events of every engine the request touched into one
causally ordered timeline; `?format=chrome` exports the merge as Chrome
trace-event JSON (Perfetto-loadable). The TraceStore spool lets any
worker of a multi-worker gateway answer `/api/traces/{id}` for requests
a sibling served — the SO_REUSEPORT blind spot.
"""

import json
import os
import time

from aiohttp import web
from aiohttp.test_utils import TestServer

from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.tracing import (
    TraceStore,
    chrome_trace,
    endpoints_touched,
    merge_timeline,
    repair_causal_order,
    _gateway_events,
)
from llmlb_tpu.gateway.worker import WorkerInfo

from tests.support import GatewayHarness, MockOpenAIEndpoint


def _trace_dict(trace_id="trace-unit-1", started_at=1000.0, spans=None,
                endpoint_name=None):
    return {
        "trace_id": trace_id,
        "started_at": started_at,
        "spans": spans or [],
        "endpoint_name": endpoint_name,
    }


def _span(name, start_ms, duration_ms=0.0, **attrs):
    span = {"name": name, "start_ms": start_ms, "duration_ms": duration_ms}
    if attrs:
        span["attrs"] = attrs
    return span


# ------------------------------------------------------------ merge: units


def test_endpoints_touched_first_touch_order_and_dedup():
    trace = _trace_dict(spans=[
        _span("endpoint_select", 1.0, endpoint="ep-a"),
        _span("handoff_adopt", 5.0, endpoint="ep-b", self_adopt=False),
        _span("stream_resume", 9.0, endpoint="ep-b"),
    ])
    assert endpoints_touched(trace) == ["ep-a", "ep-b"]


def test_endpoints_touched_falls_back_to_endpoint_name():
    # older traces (or error paths) may carry no endpoint-attributed spans
    trace = _trace_dict(spans=[_span("auth", 0.0)], endpoint_name="ep-z")
    assert endpoints_touched(trace) == ["ep-z"]
    assert endpoints_touched(_trace_dict()) == []


def test_gateway_events_carry_wall_clock_and_durations():
    trace = _trace_dict(spans=[
        _span("auth", 1.0, duration_ms=0.5),
        _span("queue_wait", 3.0, duration_ms=12.0),
        _span("endpoint_select", 16.0, endpoint="ep-a"),
    ])
    events = _gateway_events(trace)
    assert [e["event"] for e in events] == ["auth", "queue_wait",
                                           "endpoint_select"]
    assert all(e["src"] == "gateway" for e in events)
    assert [e["seq"] for e in events] == [1, 2, 3]
    assert events[1]["ts"] == 1000.003  # started_at + start_ms/1000
    assert events[1]["duration_s"] == 0.012
    assert events[2]["attrs"]["endpoint"] == "ep-a"
    assert "duration_s" not in events[2]  # marks are instants


def test_merge_timeline_repairs_cross_source_skew():
    """The disagg acceptance shape: the adopting engine's clock runs
    behind the emitter's, stamping `adopted` before `handoff_emitted`.
    The merge must not show the effect before its cause."""
    trace = _trace_dict(spans=[
        _span("endpoint_select", 1.0, endpoint="prefill-ep"),
    ])
    engine_events = [
        {"seq": 5, "ts": 1000.050, "src": "engine-pid1",
         "event": "handoff_emitted", "request_id": "trace-unit-1",
         "endpoint": "prefill-ep"},
        {"seq": 2, "ts": 1000.020, "src": "engine-pid2",
         "event": "adopted", "request_id": "trace-unit-1",
         "endpoint": "decode-ep"},
    ]
    tl = merge_timeline(trace, engine_events, sources=[])
    order = [e["event"] for e in tl["events"]]
    assert order.index("handoff_emitted") < order.index("adopted")
    adopted = next(e for e in tl["events"] if e["event"] == "adopted")
    assert adopted["ts_adjusted"] is True
    assert adopted["ts"] > 1000.050
    assert tl["trace_id"] == "trace-unit-1"
    assert tl["event_count"] == len(tl["events"]) == 3


def test_repair_clamps_failover_park_resume_across_sources():
    """SIGKILL failover: park recorded by the dead engine's spool, resume
    by the survivor — skew must not order the resume first."""
    events = [
        {"seq": 9, "ts": 50.0, "src": "engine-pid1", "event": "parked"},
        {"seq": 1, "ts": 49.5, "src": "engine-pid2", "event": "resumed"},
    ]
    repair_causal_order(events)
    assert [e["event"] for e in events] == ["parked", "resumed"]
    assert events[1]["ts_adjusted"] is True


def test_repair_leaves_same_source_cycles_alone():
    """One engine legitimately parks and resumes the same request many
    times (preemption churn); per-process seq already orders those and
    the repair must not touch them."""
    events = [
        {"seq": i + 1, "ts": float(i), "src": "engine-pid1", "event": ev}
        for i, ev in enumerate(["parked", "resumed", "parked", "resumed"])
    ]
    before = [e["ts"] for e in events]
    repair_causal_order(events)
    assert [e["ts"] for e in events] == before
    assert not any(e.get("ts_adjusted") for e in events)


def test_chrome_trace_export_shape():
    timeline = {"events": [
        {"seq": 1, "ts": 100.0, "src": "gateway", "event": "queue_wait",
         "request_id": "trace-u", "duration_s": 0.012},
        {"seq": 1, "ts": 100.005, "src": "engine-pid1", "event": "admitted",
         "request_id": "trace-u", "endpoint": "ep-a", "ts_adjusted": True},
    ]}
    out = chrome_trace(timeline)
    assert out["displayTimeUnit"] == "ms"
    json.dumps(out)  # must be serializable as-is
    meta = [e for e in out["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} == {
        "gateway", "ep-a (engine-pid1)"}
    assert len({m["pid"] for m in meta}) == 2  # one process row per source
    slice_ = next(e for e in out["traceEvents"]
                  if e["ph"] == "X")
    assert slice_["name"] == "queue_wait" and slice_["dur"] == 12000.0
    assert slice_["ts"] == 0.0  # offsets are µs from the earliest event
    instant = next(e for e in out["traceEvents"] if e["ph"] == "i")
    assert instant["name"] == "admitted" and instant["ts"] == 5000.0
    assert instant["args"]["ts_adjusted"] is True
    assert instant["args"]["request_id"] == "trace-u"


# ------------------------------------------------------- TraceStore spool


def test_spool_lets_a_sibling_store_answer(tmp_path):
    a = TraceStore(timeline_interval=1, spool_dir=str(tmp_path))
    b = TraceStore(timeline_interval=1, spool_dir=str(tmp_path))
    t = a.start("trace-sib-1", "POST", "/v1/chat/completions")
    t.mark("endpoint_select", endpoint="ep-a")
    a.finish(t, 200)
    got = b.get("trace-sib-1")  # b never saw the request
    assert got is not None and got["spooled"] is True
    assert got["in_flight"] is False and got["status"] == 200
    assert any(s["name"] == "endpoint_select" for s in got["spans"])
    # the serving store answers from memory, not its own spool file
    local = a.get("trace-sib-1")
    assert local["in_flight"] is False and "spooled" not in local


def test_spool_rejects_torn_and_mismatched_files(tmp_path):
    store = TraceStore(timeline_interval=1, spool_dir=str(tmp_path))
    (tmp_path / "trace-trace-torn.json").write_text('{"trace_id": "trace-t')
    assert store.get("trace-torn") is None
    (tmp_path / "trace-trace-lied.json").write_text(
        json.dumps({"trace_id": "other"}))
    assert store.get("trace-lied") is None


def test_spool_never_reads_outside_its_dir(tmp_path):
    store = TraceStore(timeline_interval=1, spool_dir=str(tmp_path))
    # ids with path separators fail the id regex before any open()
    assert store.get("../../etc/passwd") is None
    assert store.get("a/b") is None


def test_spool_prunes_past_retention(tmp_path):
    store = TraceStore(timeline_interval=1, spool_dir=str(tmp_path))
    t = store.start("trace-old-1", "POST", "/v1/chat/completions")
    store.finish(t, 200)
    path = tmp_path / "trace-trace-old-1.json"
    assert path.exists()
    stale = time.time() - TraceStore.SPOOL_RETENTION_S - 5
    os.utime(path, (stale, stale))
    store._prune_spool()
    assert not path.exists()
    assert store.spool_errors_total == 0


def test_spool_write_failure_counts_not_crashes(tmp_path):
    blocked = tmp_path / "not-a-dir"
    blocked.write_text("file where the spool dir should be")
    store = TraceStore(timeline_interval=1, spool_dir=str(blocked))
    t = store.start("trace-err-1", "POST", "/v1/chat/completions")
    store.finish(t, 200)  # must not raise
    assert store.spool_errors_total == 1
    assert store.get("trace-err-1")["in_flight"] is False  # ring still works


async def test_sibling_worker_state_answers_trace_lookup(tmp_path,
                                                         monkeypatch):
    """Two AppStates wired like forked workers (shared gossip dir): a
    trace finished on worker 0 is readable through worker 1's store —
    the exact `/api/traces/{id}` 404 this PR fixes."""
    monkeypatch.setenv("LLMLB_GOSSIP_DIR", str(tmp_path / "bus"))
    db_path = str(tmp_path / "gw.db")
    config = ServerConfig(port=45891, database_url=db_path)
    s0 = await build_app_state(config, db=Database(db_path),
                               start_background=False,
                               worker=WorkerInfo(index=0, count=2))
    s1 = await build_app_state(config, db=Database(db_path),
                               start_background=False,
                               worker=WorkerInfo(index=1, count=2))
    try:
        assert s0.traces.spool_dir
        assert s0.traces.spool_dir == s1.traces.spool_dir
        t = s0.traces.start("trace-xworker-1", "POST",
                            "/v1/chat/completions")
        s0.traces.finish(t, 200)
        got = s1.traces.get("trace-xworker-1")
        assert got is not None and got["spooled"] is True
    finally:
        await s0.close()
        await s1.close()


# --------------------------------------------------------------- e2e view


class MockEngineWithTimeline(MockOpenAIEndpoint):
    """OpenAI mock that also speaks the engine observability surface:
    ``GET /api/requests/{id}/timeline`` returns canned flight-recorder
    events stamped just after the chat request it served."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.src = "engine-pid99991"
        self.last_chat_ts: float | None = None

    async def start(self) -> "MockEngineWithTimeline":
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_get("/api/requests/{request_id}/timeline",
                           self._timeline)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _chat(self, request):
        self.last_chat_ts = time.time()
        return await super()._chat(request)

    async def _timeline(self, request):
        rid = request.match_info["request_id"]
        ts = self.last_chat_ts or time.time()
        events = [
            {"seq": 1, "ts": round(ts + 0.001, 6), "src": self.src,
             "event": "admitted", "request_id": rid},
            {"seq": 2, "ts": round(ts + 0.002, 6), "src": self.src,
             "event": "prefill_chunk", "request_id": rid,
             "attrs": {"tokens": 7, "cached_tokens": 0}},
            {"seq": 3, "ts": round(ts + 0.004, 6), "src": self.src,
             "event": "finished", "request_id": rid,
             "attrs": {"reason": "stop"}},
        ]
        return web.json_response({"request_id": rid, "source": self.src,
                                  "events": events})


async def test_timeline_view_joins_engine_events_e2e():
    gw = await GatewayHarness.create()
    engine = await MockEngineWithTimeline(model="m1").start()
    try:
        gw.register_mock(engine.url, ["m1"], name="ep-a")
        rid = "trace-join-e2e-1"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "messages": [{"role": "user",
                                               "content": "hi"}]},
            headers={**(await gw.inference_headers()), "X-Request-Id": rid},
        )
        assert resp.status == 200, await resp.text()

        resp = await gw.client.get(f"/api/traces/{rid}?view=timeline",
                                   headers=await gw.admin_headers())
        assert resp.status == 200, await resp.text()
        body = await resp.json()
        tl = body["timeline"]
        assert tl["endpoints"] == ["ep-a"]
        (src_info,) = tl["sources"]
        assert src_info["ok"] is True and src_info["events"] == 3
        assert src_info["source"] == engine.src

        events = tl["events"]
        by_src = {e["src"] for e in events}
        assert by_src == {"gateway", engine.src}
        engine_evs = [e for e in events if e["src"] == engine.src]
        assert [e["event"] for e in engine_evs] == [
            "admitted", "prefill_chunk", "finished"]
        assert all(e["endpoint"] == "ep-a" for e in engine_evs)
        # the merge is ordered: selection happens before engine admission
        order = [e["event"] for e in events]
        assert order.index("endpoint_select") < order.index("admitted")
        tss = [e["ts"] for e in events]
        assert tss == sorted(tss)
    finally:
        await engine.stop()
        await gw.close()


async def test_chrome_format_exports_perfetto_loadable_json():
    gw = await GatewayHarness.create()
    engine = await MockEngineWithTimeline(model="m1").start()
    try:
        gw.register_mock(engine.url, ["m1"], name="ep-a")
        rid = "trace-chrome-e2e-1"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "messages": [{"role": "user",
                                               "content": "hi"}]},
            headers={**(await gw.inference_headers()), "X-Request-Id": rid},
        )
        assert resp.status == 200, await resp.text()

        resp = await gw.client.get(f"/api/traces/{rid}?format=chrome",
                                   headers=await gw.admin_headers())
        assert resp.status == 200
        body = await resp.json()
        events = body["traceEvents"]
        assert events and body["displayTimeUnit"] == "ms"
        names = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert "gateway" in names
        assert f"ep-a ({engine.src})" in names
        assert all(e["ph"] in ("M", "X", "i") for e in events)
        assert all(e["ts"] >= 0 for e in events if e["ph"] != "M")
    finally:
        await engine.stop()
        await gw.close()


async def test_timeline_view_reports_unreachable_engine():
    """An endpoint with no timeline surface (or a dead one) degrades to a
    per-source error — the gateway's own events still render."""
    gw = await GatewayHarness.create()
    upstream = await MockOpenAIEndpoint(model="m1").start()
    try:
        gw.register_mock(upstream.url, ["m1"], name="ep-a")
        rid = "trace-degraded-1"
        resp = await gw.client.post(
            "/v1/chat/completions",
            json={"model": "m1", "messages": [{"role": "user",
                                               "content": "hi"}]},
            headers={**(await gw.inference_headers()), "X-Request-Id": rid},
        )
        assert resp.status == 200, await resp.text()

        resp = await gw.client.get(f"/api/traces/{rid}?view=timeline",
                                   headers=await gw.admin_headers())
        assert resp.status == 200
        tl = (await resp.json())["timeline"]
        (src_info,) = tl["sources"]
        assert src_info["ok"] is False and "404" in src_info["error"]
        assert tl["events"] and all(e["src"] == "gateway"
                                    for e in tl["events"])
    finally:
        await upstream.stop()
        await gw.close()
