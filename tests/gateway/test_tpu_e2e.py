"""The end-to-end slice (BASELINE.json config #2 shape): a real in-tree tpu://
engine registered into the gateway, detected as TPU type, models synced, tokens
streamed through /v1/chat/completions and /v1/responses with usage accounting
and TPU telemetry flowing into the registry.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestServer

from llmlb_tpu.engine.server import create_engine_app
from llmlb_tpu.engine.service import Engine
from llmlb_tpu.gateway.health import EndpointHealthChecker
from llmlb_tpu.gateway.types import EndpointStatus, EndpointType, TpsApiKind
from tests.support import GatewayHarness


@pytest.fixture(scope="module")
def engine():
    eng = Engine.from_preset(
        "debug-tiny", model_id="tpu-tiny", num_slots=4, slot_capacity=128,
        prefill_buckets=(16, 32, 64),
    )
    yield eng
    eng.shutdown()


def test_tpu_engine_through_gateway(engine):
    async def run():
        gw = await GatewayHarness.create()
        engine_server = TestServer(create_engine_app(engine, owns_engine=False))
        await engine_server.start_server()
        engine_url = f"http://127.0.0.1:{engine_server.port}"
        gw.state.health_checker = EndpointHealthChecker(
            gw.state.registry, gw.state.load_manager, gw.state.db,
            gw.state.http, gw.state.events, interval_s=3600, timeout_s=5.0,
        )
        try:
            headers = await gw.admin_headers()
            # register: the gateway must auto-detect the tpu endpoint type
            r = await gw.client.post("/api/endpoints", json={
                "base_url": engine_url, "name": "tpu0"}, headers=headers)
            assert r.status == 201, await r.text()
            created = await r.json()
            assert created["endpoint_type"] == "tpu"
            assert created["status"] == "online"
            assert [m["model_id"] for m in created["models"]] == ["tpu-tiny"]

            iheaders = await gw.inference_headers()

            # non-stream chat through the gateway
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-tiny", "max_tokens": 5, "temperature": 0,
                "messages": [{"role": "user", "content": "hello tpu"}],
            }, headers=iheaders)
            assert r.status == 200, await r.text()
            body = await r.json()
            assert body["usage"]["completion_tokens"] >= 1

            # streaming chat: SSE passes through, usage lands in TPS tracker
            r = await gw.client.post("/v1/chat/completions", json={
                "model": "tpu-tiny", "max_tokens": 5, "temperature": 0,
                "stream": True,
                "messages": [{"role": "user", "content": "hello tpu"}],
            }, headers=iheaders)
            assert r.status == 200
            raw = (await r.read()).decode()
            assert raw.strip().endswith("data: [DONE]")
            usage_chunks = [
                json.loads(l[6:]) for l in raw.splitlines()
                if l.startswith("data: ") and l != "data: [DONE]"
            ]
            assert any(c.get("usage") for c in usage_chunks)

            ep_id = created["id"]
            await asyncio.sleep(0.05)
            assert gw.state.load_manager.get_tps(
                ep_id, "tpu-tiny", TpsApiKind.CHAT) is not None

            # /v1/responses through the gateway (the north-star path)
            r = await gw.client.post("/v1/responses", json={
                "model": "tpu-tiny", "input": "ping", "max_output_tokens": 4,
            }, headers=iheaders)
            assert r.status == 200
            resp_body = await r.json()
            assert resp_body["status"] == "completed"
            assert resp_body["usage"]["output_tokens"] >= 1

            # health probe pulled TPU telemetry into the registry
            ep = gw.state.registry.get(ep_id)
            await gw.state.health_checker.check_endpoint(ep)
            ep = gw.state.registry.get(ep_id)
            assert ep.status == EndpointStatus.ONLINE
            assert ep.accelerator.chip_count >= 1
            assert ep.endpoint_type == EndpointType.TPU

            # dashboard overview shows the chip count
            r = await gw.client.get("/api/dashboard/overview", headers=headers)
            ov = await r.json()
            assert ov["tpu"]["total_chips"] >= 1
        finally:
            await engine_server.close()
            await gw.close()
    asyncio.run(run())
