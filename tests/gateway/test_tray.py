"""Tray controller: menu model follows update state, notifications fire on
available/failed, menu activation proxies into the UpdateManager.

Parity target: reference gui/tray.rs:37-135 (tray menu composition + event
proxy into the update manager). Our backend is headless; the controller logic
is the same surface a GUI backend would drive.
"""

import asyncio

import pytest

from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.gate import InferenceGate
from llmlb_tpu.gateway.tray import HeadlessTrayBackend, TrayController
from llmlb_tpu.gateway.update import UpdateManager, UpdateState


def _menu_ids(tray):
    return [i["id"] for i in tray.backend.menu]


def _item(tray, item_id):
    return next(i for i in tray.backend.menu if i["id"] == item_id)


@pytest.fixture
def update_manager():
    return UpdateManager(InferenceGate(), events=DashboardEventBus())


def test_menu_model_baseline(update_manager):
    tray = TrayController("http://x/dashboard", update_manager)
    assert _menu_ids(tray) == ["open_dashboard", "update", "schedule", "quit"]
    assert _item(tray, "update")["label"] == "Check for updates"
    assert _item(tray, "schedule")["label"] == "Update schedule: immediate"
    assert _item(tray, "schedule")["enabled"] is False


def test_menu_follows_update_state(update_manager):
    tray = TrayController("http://x/dashboard", update_manager)
    update_manager.available_version = "v2.0.0"
    update_manager.state = UpdateState.AVAILABLE
    tray.refresh()
    assert "v2.0.0" in _item(tray, "update")["label"]
    assert _item(tray, "update")["enabled"] is True

    update_manager.state = UpdateState.DRAINING
    tray.refresh()
    assert "draining" in _item(tray, "update")["label"].lower()
    assert _item(tray, "update")["enabled"] is False

    update_manager.state = UpdateState.FAILED
    update_manager.error = "disk full"
    tray.refresh()
    assert "disk full" in _item(tray, "update")["label"]


def test_schedule_display(update_manager):
    tray = TrayController("http://x/dashboard", update_manager)
    update_manager.set_schedule("on_idle")
    tray.refresh()
    assert _item(tray, "schedule")["label"] == "Update schedule: when idle"


@pytest.mark.asyncio
async def test_activate_check_and_apply(update_manager):
    checks = []

    async def check_hook():
        checks.append(1)
        return {"version": "v3.0.0"}

    update_manager.check_hook = check_hook
    applied = asyncio.Event()

    async def apply_hook():
        applied.set()

    update_manager.apply_hook = apply_hook
    tray = TrayController("http://x/dashboard", update_manager)

    # no update known yet → activation runs a forced check
    result = await tray.activate("update")
    assert result["action"] == "check" and checks
    assert update_manager.state == UpdateState.AVAILABLE

    # update now available → activation requests the apply
    result = await tray.activate("update")
    assert result["action"] == "apply" and result["ok"]
    await asyncio.wait_for(applied.wait(), 5)


@pytest.mark.asyncio
async def test_open_dashboard_and_quit(update_manager):
    opened, quit_called = [], []
    tray = TrayController(
        "http://gw:1234/dashboard", update_manager,
        open_url_cb=opened.append, quit_cb=lambda: quit_called.append(1),
    )
    assert (await tray.activate("open_dashboard"))["ok"]
    assert opened == ["http://gw:1234/dashboard"]
    assert (await tray.activate("quit"))["ok"] and quit_called
    assert not (await tray.activate("nonsense"))["ok"]


@pytest.mark.asyncio
async def test_event_bus_notification(update_manager):
    """UpdateStateChanged(available) on the bus → one tray notification and a
    refreshed menu; repeated events for the same version don't re-notify."""
    events = update_manager.events
    tray = TrayController(
        "http://x/dashboard", update_manager, events=events,
        backend=HeadlessTrayBackend(),
    )
    await tray.start()
    try:
        update_manager.available_version = "v5.0.0"
        update_manager.state = UpdateState.AVAILABLE
        events.publish(
            "UpdateStateChanged", {"state": "available", "version": "v5.0.0"}
        )
        for _ in range(100):
            if tray.backend.notifications:
                break
            await asyncio.sleep(0.01)
        assert len(tray.backend.notifications) == 1
        assert "v5.0.0" in tray.backend.notifications[0]["body"]
        assert "v5.0.0" in _item(tray, "update")["label"]

        events.publish(
            "UpdateStateChanged", {"state": "available", "version": "v5.0.0"}
        )
        await asyncio.sleep(0.05)
        assert len(tray.backend.notifications) == 1  # deduped
    finally:
        await tray.stop()


@pytest.mark.asyncio
async def test_tray_http_surface():
    """/api/system/tray reports disabled without a controller, and serves the
    menu + activation proxy once one is attached (headless tray's 'display')."""
    from tests.support import GatewayHarness

    gw = await GatewayHarness.create()
    try:
        headers = await gw.admin_headers()
        resp = await gw.client.get("/api/system/tray", headers=headers)
        assert resp.status == 200
        assert (await resp.json()) == {"enabled": False}

        resp = await gw.client.post(
            "/api/system/tray/activate", json={"item": "update"},
            headers=headers,
        )
        assert resp.status == 404

        update = UpdateManager(gw.state.gate, events=gw.state.events)

        async def check_hook():
            return {"version": "v7.7.7"}

        update.check_hook = check_hook
        gw.state.tray = TrayController("http://x/dashboard", update)

        resp = await gw.client.get("/api/system/tray", headers=headers)
        body = await resp.json()
        assert body["enabled"] is True
        assert [i["id"] for i in body["menu"]] == [
            "open_dashboard", "update", "schedule", "quit",
        ]

        resp = await gw.client.post(
            "/api/system/tray/activate", json={"item": "update"},
            headers=headers,
        )
        assert resp.status == 200
        assert (await resp.json())["action"] == "check"
        assert update.state == UpdateState.AVAILABLE

        # bad credentials are refused like the rest of /api/* (a bare GET
        # would ride the admin session cookie the login above set)
        resp = await gw.client.get(
            "/api/system/tray", headers={"Authorization": "Bearer bogus"}
        )
        assert resp.status == 401
    finally:
        await gw.close()
