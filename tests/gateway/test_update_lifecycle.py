"""Self-update lifecycle: GitHub check → download w/ progress → drain →
artifact swap with .bak → post-restart health watch → rollback on unhealthy.

Parity targets: update/mod.rs:59-123 (state machine), :807-965 (background
check + download), schedule.rs:17-90, README.md:160-166 (rollback).
"""

import asyncio
import json
import os
import time

from aiohttp import web
from aiohttp.test_utils import TestServer

from llmlb_tpu.gateway.events import DashboardEventBus
from llmlb_tpu.gateway.gate import InferenceGate
from llmlb_tpu.gateway.update import ApplyMode, UpdateManager, UpdateState
from llmlb_tpu.gateway.update_source import (
    ArtifactSwapApplier,
    GitHubUpdateSource,
    is_newer,
)


class MockGitHub:
    """Minimal GitHub Releases API: latest release + one downloadable asset."""

    def __init__(self, version="v9.9.9", asset=b"NEW ARTIFACT BYTES " * 64):
        self.version = version
        self.asset = asset
        self.check_count = 0
        self.server: TestServer | None = None

    @property
    def api_base(self) -> str:
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self):
        app = web.Application()
        app.router.add_get(
            "/repos/acme/llmlb-tpu/releases/latest", self._latest
        )
        app.router.add_get("/assets/app.bin", self._asset)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def stop(self):
        await self.server.close()

    async def _latest(self, request):
        self.check_count += 1
        return web.json_response({
            "tag_name": self.version,
            "body": "release notes",
            "assets": [{
                "name": "app.bin",
                "browser_download_url": f"{self.api_base}/assets/app.bin",
            }],
        })

    async def _asset(self, request):
        return web.Response(
            body=self.asset,
            headers={"Content-Length": str(len(self.asset))},
        )


def test_version_comparison():
    assert is_newer("v2.0.0", "1.9.9")
    assert is_newer("1.10.0", "1.9.0")
    assert not is_newer("1.0.0", "1.0.0")
    assert not is_newer("v0.9.0", "1.0.0")


def test_full_update_lifecycle(tmp_path):
    async def run():
        import aiohttp

        gh = await MockGitHub().start()
        artifact = tmp_path / "app.bin"
        artifact.write_bytes(b"OLD ARTIFACT")
        restarts = []

        async with aiohttp.ClientSession() as http:
            gate = InferenceGate()
            events = DashboardEventBus()
            mgr = UpdateManager(
                gate, events, drain_timeout_s=2.0,
                source=GitHubUpdateSource(
                    http, "acme/llmlb-tpu", "1.0.0",
                    api_base=gh.api_base,
                ),
                applier=ArtifactSwapApplier(str(artifact)),
                restart_cb=lambda: restarts.append(time.time()),
            )

            # ---- check: finds the newer release
            res = await mgr.check()
            assert res["available"] and res["version"] == "v9.9.9"
            assert mgr.state == UpdateState.AVAILABLE

            # 24h cache: a second check does not re-hit the API
            n = gh.check_count
            await mgr.check()
            assert gh.check_count == n

            # ---- drain semantics: one slow in-flight inference delays apply
            async def fake_inference():
                with gate.track():
                    await asyncio.sleep(0.3)

            inflight = asyncio.create_task(fake_inference())
            await asyncio.sleep(0.05)
            assert mgr.request_apply(ApplyMode.NORMAL)
            assert not mgr.request_apply(ApplyMode.NORMAL)  # one at a time
            await asyncio.sleep(0.05)
            assert mgr.state == UpdateState.DRAINING
            assert gate.rejecting  # /v1/* would 503 now
            await inflight
            await mgr._apply_task

            # ---- artifact swapped, .bak kept, marker written, restart fired
            assert artifact.read_bytes() == gh.asset
            assert (tmp_path / "app.bin.bak").read_bytes() == b"OLD ARTIFACT"
            marker = json.loads((tmp_path / "update_pending.json").read_text())
            assert marker["version"] == "v9.9.9"
            assert len(restarts) == 1
            assert mgr.download_progress["done"] == len(gh.asset)
            assert mgr.history[-1]["ok"] is True

            # ---- post-restart watch: healthy clears the marker
            async def healthy():
                return True

            out = await mgr.post_restart_watch(
                healthy, watch_s=2.0, interval_s=0.01
            )
            assert out == "healthy"
            assert not os.path.exists(tmp_path / "update_pending.json")

        await gh.stop()

    asyncio.run(run())


def test_post_restart_rollback_on_unhealthy(tmp_path):
    async def run():
        artifact = tmp_path / "app.bin"
        artifact.write_bytes(b"BROKEN NEW VERSION")
        (tmp_path / "app.bin.bak").write_bytes(b"GOOD OLD VERSION")
        applier = ArtifactSwapApplier(str(artifact))
        applier.write_marker("v9.9.9")
        restarts = []
        mgr = UpdateManager(
            InferenceGate(), applier=applier,
            restart_cb=lambda: restarts.append(1),
        )

        async def never_healthy():
            return False

        out = await mgr.post_restart_watch(
            never_healthy, watch_s=0.2, interval_s=0.02
        )
        assert out == "rolled_back"
        assert artifact.read_bytes() == b"GOOD OLD VERSION"
        assert not os.path.exists(tmp_path / "update_pending.json")
        assert mgr.state == UpdateState.FAILED
        assert restarts == [1]  # re-exec back into the old version

    asyncio.run(run())


def test_schedule_on_idle_and_at_time(tmp_path):
    async def run():
        gate = InferenceGate()
        applied = []

        async def apply_hook():
            applied.append(time.time())

        mgr = UpdateManager(gate, apply_hook=apply_hook, drain_timeout_s=0.5)
        mgr.available_version = "v2.0.0"
        mgr._set_state(UpdateState.AVAILABLE)

        # speed the tick up for the test
        import llmlb_tpu.gateway.update as upd

        old_tick = upd.SCHEDULE_TICK_S
        upd.SCHEDULE_TICK_S = 0.02
        try:
            mgr.set_schedule("on_idle")
            mgr.start_background_tasks(check_interval_s=3600)
            # busy: no apply
            with gate.track():
                await asyncio.sleep(0.1)
                assert not applied
            # idle: schedule fires
            for _ in range(100):
                if applied:
                    break
                await asyncio.sleep(0.02)
            assert applied, "on_idle schedule never fired"

            # at_time: fires once the clock passes
            applied.clear()
            mgr.available_version = "v2.1.0"
            mgr._set_state(UpdateState.AVAILABLE)
            mgr.set_schedule("at_time", time.time() + 0.15)
            for _ in range(100):
                if applied:
                    break
                await asyncio.sleep(0.02)
            assert applied, "at_time schedule never fired"
            assert mgr.schedule.mode == "immediate"  # one-shot reset
        finally:
            upd.SCHEDULE_TICK_S = old_tick
            await mgr.stop_background_tasks()

    asyncio.run(run())


def test_check_failure_is_reported_not_raised(tmp_path):
    async def run():
        import aiohttp

        async with aiohttp.ClientSession() as http:
            mgr = UpdateManager(
                InferenceGate(),
                source=GitHubUpdateSource(
                    http, "acme/x", "1.0.0",
                    api_base="http://127.0.0.1:1",  # nothing listening
                ),
            )
            res = await mgr.check()
            assert res["available"] is False
            assert "error" in res

    asyncio.run(run())


def test_rolled_back_version_is_not_reoffered(tmp_path):
    """A release that failed its health watch is blocklisted on disk and
    skipped by subsequent checks (no apply/rollback flip-flop)."""

    async def run():
        artifact = tmp_path / "app.bin"
        artifact.write_bytes(b"BROKEN")
        (tmp_path / "app.bin.bak").write_bytes(b"OLD")
        applier = ArtifactSwapApplier(str(artifact))
        applier.write_marker("v9.9.9")
        mgr = UpdateManager(InferenceGate(), applier=applier)

        async def never_healthy():
            return False

        out = await mgr.post_restart_watch(
            never_healthy, watch_s=0.1, interval_s=0.02
        )
        assert out == "rolled_back"

        # a fresh manager (simulated restart) must skip the bad release
        async def offers_v999():
            return {"version": "v9.9.9", "asset_url": "http://x/a"}

        mgr2 = UpdateManager(InferenceGate(), applier=ArtifactSwapApplier(
            str(artifact)), check_hook=offers_v999)
        res = await mgr2.check()
        assert res["available"] is False
        assert res.get("blocked") == "v9.9.9"
        assert mgr2.state == UpdateState.UP_TO_DATE

    asyncio.run(run())


def test_apply_without_asset_fails_before_draining(tmp_path):
    """A release with no matching asset must fail fast, not 503 traffic."""

    async def run():
        import aiohttp

        gh = await MockGitHub().start()
        artifact = tmp_path / "app.bin"
        artifact.write_bytes(b"OLD")
        async with aiohttp.ClientSession() as http:
            gate = InferenceGate()
            src = GitHubUpdateSource(http, "acme/llmlb-tpu", "1.0.0",
                                     asset_match="no-such-asset",
                                     api_base=gh.api_base)
            mgr = UpdateManager(gate, source=src,
                                applier=ArtifactSwapApplier(str(artifact)))
            res = await mgr.check()
            assert res["available"] and res["asset_url"] is None
            assert mgr.request_apply(ApplyMode.NORMAL)
            await mgr._apply_task
            assert mgr.state == UpdateState.FAILED
            assert not gate.rejecting  # traffic was never drained
            assert artifact.read_bytes() == b"OLD"
            assert mgr.history[-1]["ok"] is False
        await gh.stop()

    asyncio.run(run())


def test_apply_with_no_mechanism_is_recorded_as_failure():
    async def run():
        gate = InferenceGate()
        mgr = UpdateManager(gate)  # no hook, no applier
        mgr.available_version = "v2.0.0"
        mgr._set_state(UpdateState.AVAILABLE)
        assert mgr.request_apply(ApplyMode.NORMAL)
        await mgr._apply_task
        assert mgr.state == UpdateState.FAILED
        assert "no apply mechanism" in (mgr.error or "")
        assert not gate.rejecting

    asyncio.run(run())
