"""LoRA adapter store + manager + request-surface units (docs/lora.md)."""

import json
import os

import numpy as np
import pytest

from llmlb_tpu.engine.presets import get_preset
from llmlb_tpu.lora import (
    adapter_from_body,
    discover_adapters,
    load_adapter_tensors,
    lora_target_dims,
    save_adapter,
    split_model_adapter,
)
from llmlb_tpu.lora.manager import LoraManager

CFG = get_preset("debug-tiny")
ALL_TARGETS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd")


# --------------------------------------------------------------------- store


def test_save_discover_roundtrip(tmp_path):
    save_adapter(str(tmp_path), "acme", CFG, rank=4, alpha=8.0,
                 targets=("wq", "wv"))
    found = discover_adapters(str(tmp_path), rank_cap=16,
                              allowed_targets=ALL_TARGETS)
    assert set(found) == {"acme"}
    info = found["acme"]
    assert info.error is None
    assert info.rank == 4 and info.alpha == 8.0
    assert info.targets == ("wq", "wv")


def test_rank_over_cap_is_recorded_not_raised(tmp_path):
    save_adapter(str(tmp_path), "fat", CFG, rank=32)
    found = discover_adapters(str(tmp_path), rank_cap=16,
                              allowed_targets=ALL_TARGETS)
    assert found["fat"].error is not None
    assert "rank 32" in found["fat"].error


def test_unsupported_target_module_is_recorded(tmp_path):
    path = save_adapter(str(tmp_path), "weird", CFG, rank=2)
    cfgp = os.path.join(path, "adapter_config.json")
    with open(cfgp) as f:
        cfg = json.load(f)
    cfg["target_modules"] = ["embed_tokens"]
    with open(cfgp, "w") as f:
        json.dump(cfg, f)
    found = discover_adapters(str(tmp_path), rank_cap=16,
                              allowed_targets=ALL_TARGETS)
    assert "embed_tokens" in (found["weird"].error or "")


def test_load_tensors_shapes_rank_pad_and_alpha_fold(tmp_path):
    save_adapter(str(tmp_path), "acme", CFG, rank=4, alpha=8.0,
                 targets=("wq",), scale=1.0)
    found = discover_adapters(str(tmp_path), rank_cap=16,
                              allowed_targets=ALL_TARGETS)
    host = load_adapter_tensors(found["acme"], CFG, pool_rank=16,
                                dtype=np.float32)
    assert set(host) == {"wq"}
    a, b = host["wq"]
    in_dim, out_dim = lora_target_dims(CFG, ("wq",))["wq"]
    assert a.shape == (CFG.num_layers, in_dim, 16)
    assert b.shape == (CFG.num_layers, 16, out_dim)
    # rank pads with exact zeros beyond r=4
    assert np.all(a[:, :, 4:] == 0) and np.all(b[:, 4:, :] == 0)
    assert np.any(a[:, :, :4] != 0)
    # alpha/r = 2.0 folded into B: reload with alpha=r and compare
    save_adapter(str(tmp_path), "acme2", CFG, rank=4, alpha=4.0,
                 targets=("wq",), scale=1.0)
    found2 = discover_adapters(str(tmp_path), rank_cap=16,
                               allowed_targets=ALL_TARGETS)
    host2 = load_adapter_tensors(found2["acme2"], CFG, pool_rank=16,
                                 dtype=np.float32)
    # same name-derived RNG seed is per-name, so compare magnitudes via
    # the fold factor on one adapter instead: B scales linearly in alpha
    save_adapter(str(tmp_path), "acme", CFG, rank=4, alpha=16.0,
                 targets=("wq",), scale=1.0)
    found3 = discover_adapters(str(tmp_path), rank_cap=16,
                               allowed_targets=ALL_TARGETS)
    host3 = load_adapter_tensors(found3["acme"], CFG, pool_rank=16,
                                 dtype=np.float32)
    np.testing.assert_allclose(host3["wq"][1], 2.0 * b, rtol=1e-6)
    del host2


# ----------------------------------------------------------------- request api


def test_split_model_adapter():
    assert split_model_adapter("m:acme") == ("m", "acme")
    assert split_model_adapter("m") == ("m", None)
    assert split_model_adapter(None) == (None, None)
    # empty base or non-name suffix stays a literal model string
    assert split_model_adapter(":acme") == (":acme", None)
    assert split_model_adapter("m:!bad!") == ("m:!bad!", None)


def test_adapter_from_body_field_and_suffix():
    assert adapter_from_body({"model": "m", "lora": "a"}) == ("m", "a")
    assert adapter_from_body({"model": "m:a"}) == ("m", "a")
    assert adapter_from_body({"model": "m:a", "lora": "a"}) == ("m", "a")
    assert adapter_from_body({"model": "m"}) == ("m", None)


@pytest.mark.parametrize("body,needle", [
    ({"model": "m", "lora": 7}, "'lora'"),
    ({"model": "m", "lora": ""}, "'lora'"),
    ({"model": "m", "lora": "bad name"}, "'lora'"),
    ({"model": "m:a", "lora": "b"}, "conflicts"),
])
def test_adapter_from_body_rejects_naming_field(body, needle):
    with pytest.raises(ValueError, match=needle):
        adapter_from_body(body)


# --------------------------------------------------------------------- manager


class _FakeCore:
    """Just enough of EngineCore for the manager's device writes."""

    def __init__(self, mgr):
        import jax.numpy as jnp

        self.params = {
            k: jnp.asarray(v) for k, v in mgr.init_pool_leaves(
                np.float32
            ).items()
        }


def _manager(tmp_path, names=("a1", "a2", "a3"), max_adapters=2,
             rank=2):
    for n in names:
        save_adapter(str(tmp_path), n, CFG, rank=rank, targets=("wq",))
    mgr = LoraManager(CFG, lora_dir=str(tmp_path),
                      max_adapters=max_adapters, rank_cap=8,
                      targets=ALL_TARGETS)
    mgr.attach(_FakeCore(mgr))
    return mgr


def test_manager_acquire_loads_and_is_idempotent(tmp_path):
    mgr = _manager(tmp_path)
    row = mgr.acquire("a1", "req1")
    assert row == 1
    assert mgr.acquire("a1", "req1") == row  # idempotent per token
    assert mgr.loads_total == 1
    assert mgr.slot_of("a1") == row
    assert mgr.slot_of(None) == 0
    # device rows actually written
    import jax.numpy as jnp

    assert float(jnp.abs(mgr.core.params["wq_lora_a"][:, row]).sum()) > 0


def test_manager_lru_evicts_only_idle(tmp_path):
    mgr = _manager(tmp_path, max_adapters=2)
    mgr.acquire("a1", "r1")
    mgr.acquire("a2", "r2")
    # pool full, both active: third adapter must be refused
    with pytest.raises(ValueError, match="pool exhausted"):
        mgr.acquire("a3", "r3")
    mgr.release("r1")  # a1 idle now
    row = mgr.acquire("a3", "r3")
    assert mgr.evictions_total == 1
    assert "a1" not in mgr.resident_names()
    assert {"a2", "a3"} <= set(mgr.resident_names())
    assert row == mgr.slot_of("a3")


def test_manager_release_is_idempotent(tmp_path):
    mgr = _manager(tmp_path)
    mgr.acquire("a1", "r1")
    mgr.release("r1")
    mgr.release("r1")  # second release must not underflow another holder
    mgr.acquire("a1", "r2")
    mgr.release("r1")  # stale token again: still a no-op
    # r2 still holds a refcount: the forced eviction below (pool of 2,
    # third adapter arrives) must evict idle a2, never active a1
    mgr.acquire("a2", "x1")
    mgr.release("x1")
    mgr.acquire("a3", "x2")
    assert "a1" in mgr.resident_names()
    assert "a2" not in mgr.resident_names()


def test_manager_unknown_and_invalid_name_400_shape(tmp_path):
    mgr = _manager(tmp_path)
    with pytest.raises(ValueError, match="'lora' names unknown adapter"):
        mgr.validate("nope")
    save_adapter(str(tmp_path), "fat", CFG, rank=64)
    with pytest.raises(ValueError, match="rank 64"):
        mgr.validate("fat")  # rescan picks it up, error names the cause


def test_manager_rescan_discovers_new_adapters(tmp_path):
    mgr = _manager(tmp_path, names=("a1",))
    assert mgr.available_names() == ["a1"]
    save_adapter(str(tmp_path), "late", CFG, rank=2, targets=("wq",))
    # validate() rescans on a miss, so the new adapter is acquirable
    assert mgr.acquire("late", "r") >= 1
