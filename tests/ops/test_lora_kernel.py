"""bgmv LoRA-delta kernel: XLA gather path vs Pallas interpret-mode parity
(ops/lora.py), the quant/paged-attention kernel testing pattern."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from llmlb_tpu.ops.lora import lora_delta, lora_delta_pallas, lora_delta_xla


def _pools(key, n=4, in_dim=64, r=8, out_dim=96, dtype=jnp.bfloat16):
    ka, kb = jax.random.split(key)
    a = (jax.random.normal(ka, (n, in_dim, r), jnp.float32) * 0.1)
    b = (jax.random.normal(kb, (n, r, out_dim), jnp.float32) * 0.1)
    # row 0 is the identity adapter: all-zero by contract
    a = a.at[0].set(0.0).astype(dtype)
    b = b.at[0].set(0.0).astype(dtype)
    return a, b


@pytest.mark.parametrize("t", [1, 7, 16])  # decode, ragged chunk, prefill
def test_pallas_interpret_matches_xla(t):
    key = jax.random.PRNGKey(0)
    a, b = _pools(key)
    x = jax.random.normal(jax.random.PRNGKey(1), (5, t, 64),
                          jnp.float32).astype(jnp.bfloat16)
    idx = jnp.asarray([0, 1, 3, 1, 2], jnp.int32)
    ref = lora_delta_xla(x, a, b, idx)
    got = lora_delta_pallas(x, a, b, idx, interpret=True)
    assert ref.dtype == got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-3)


def test_identity_row_is_exact_zero():
    a, b = _pools(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (3, 4, 64),
                          jnp.float32).astype(jnp.bfloat16)
    idx = jnp.zeros((3,), jnp.int32)
    for fn in (lora_delta_xla,
               lambda *args: lora_delta_pallas(*args, interpret=True)):
        out = np.asarray(fn(x, a, b, idx))
        assert np.all(out == 0.0), "identity row delta must be exactly 0.0"


def test_xla_matches_per_row_dense_reference():
    """Each row's batched delta equals the plain two-matmul computation of
    ITS adapter — the gather introduces no cross-row mixing."""
    a, b = _pools(jax.random.PRNGKey(4), dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 3, 64), jnp.float32)
    idx = jnp.asarray([2, 0, 1, 2], jnp.int32)
    out = np.asarray(lora_delta_xla(x, a, b, idx))
    for row in range(4):
        ref = np.asarray(x[row] @ a[idx[row]] @ b[idx[row]])
        np.testing.assert_allclose(out[row], ref, rtol=1e-5, atol=1e-5)


def test_dispatcher_env_override(monkeypatch):
    """LLMLB_TPU_LORA=xla forces the gather path on any backend (and the
    call works end to end through the dispatcher)."""
    monkeypatch.setenv("LLMLB_TPU_LORA", "xla")
    a, b = _pools(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 1, 64),
                          jnp.float32).astype(jnp.bfloat16)
    idx = jnp.asarray([1, 2], jnp.int32)
    out = lora_delta(x, a, b, idx)
    ref = lora_delta_xla(x, a, b, idx)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
