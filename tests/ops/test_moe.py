"""MoE dispatch/combine + Mixtral model: correctness vs a dense per-token
reference, capacity-drop semantics, and expert-parallel sharding equivalence
on the virtual 8-device CPU mesh (SURVEY.md §4 strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.ops.moe import default_capacity, moe_dispatch_combine, top_k_routing
from llmlb_tpu.parallel.mesh import MeshConfig, build_mesh


def _dense_reference(x, logits, wg, wu, wd, k):
    """Per-token loop: exact top-k MoE with no capacity limit."""
    s, m = x.shape
    weights, idx = top_k_routing(jnp.asarray(logits, jnp.float32), k)
    weights, idx = np.asarray(weights), np.asarray(idx)
    x, wg, wu, wd = map(np.asarray, (x, wg, wu, wd))
    out = np.zeros_like(x)
    for t in range(s):
        for j in range(k):
            e = idx[t, j]
            h = x[t] @ wg[e]
            h = (h / (1 + np.exp(-h))) * (x[t] @ wu[e])
            out[t] += weights[t, j] * (h @ wd[e])
    return out


def _rand_moe(key, s, m, f, e):
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (s, m), jnp.float32)
    logits = jax.random.normal(ks[1], (s, e), jnp.float32)
    wg = jax.random.normal(ks[2], (e, m, f), jnp.float32) * m**-0.5
    wu = jax.random.normal(ks[3], (e, m, f), jnp.float32) * m**-0.5
    wd = jax.random.normal(ks[4], (e, f, m), jnp.float32) * f**-0.5
    return x, logits, wg, wu, wd


@pytest.mark.parametrize("k", [1, 2])
def test_moe_matches_dense_reference(k):
    s, m, f, e = 32, 16, 24, 4
    x, logits, wg, wu, wd = _rand_moe(jax.random.PRNGKey(0), s, m, f, e)
    # capacity = s: no token can overflow even if routing is maximally skewed
    got = moe_dispatch_combine(x, logits, wg, wu, wd, num_selected=k, capacity=s)
    want = _dense_reference(x, logits, wg, wu, wd, k)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_tokens_not_crashes():
    s, m, f, e = 64, 8, 12, 2
    x, logits, wg, wu, wd = _rand_moe(jax.random.PRNGKey(1), s, m, f, e)
    got = moe_dispatch_combine(x, logits, wg, wu, wd, num_selected=2, capacity=4)
    assert np.isfinite(np.asarray(got)).all()
    # with tiny capacity most tokens must be dropped → output mostly zeros
    dropped = (np.abs(np.asarray(got)).sum(-1) == 0).sum()
    assert dropped > 0


def test_moe_ep_sharded_matches_unsharded(cpu_mesh_devices):
    mesh = build_mesh(MeshConfig(dp=1, sp=1, ep=4, tp=2), devices=cpu_mesh_devices)
    s, m, f, e = 32, 16, 24, 4
    x, logits, wg, wu, wd = _rand_moe(jax.random.PRNGKey(2), s, m, f, e)
    plain = moe_dispatch_combine(x, logits, wg, wu, wd, num_selected=2, capacity=s)
    sharded = jax.jit(
        lambda *a: moe_dispatch_combine(
            *a, num_selected=2, capacity=s, mesh=mesh
        )
    )(x, logits, wg, wu, wd)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(plain), rtol=1e-5, atol=1e-5
    )


def test_token_valid_keeps_padding_out_of_capacity():
    """Padding tokens must not consume expert capacity: real tokens' outputs
    with a mostly-padded batch == the same tokens alone at the same capacity."""
    s_real, pad, m, f, e = 8, 56, 8, 12, 2
    x, logits, wg, wu, wd = _rand_moe(jax.random.PRNGKey(8), s_real, m, f, e)
    cap = 8  # tight: 56 identical pad tokens would saturate both experts

    alone = moe_dispatch_combine(
        x, logits, wg, wu, wd, num_selected=2, capacity=cap
    )

    x_pad = jnp.concatenate([x, jnp.ones((pad, m), jnp.float32)])
    logits_pad = jnp.concatenate(
        [logits, jnp.full((pad, e), 5.0, jnp.float32)]
    )
    valid = jnp.arange(s_real + pad) < s_real
    padded = moe_dispatch_combine(
        x_pad, logits_pad, wg, wu, wd, num_selected=2, capacity=cap,
        token_valid=valid,
    )
    np.testing.assert_allclose(
        np.asarray(padded[:s_real]), np.asarray(alone), rtol=1e-5, atol=1e-5
    )
    # and the padding rows contribute nothing
    assert np.abs(np.asarray(padded[s_real:])).max() == 0.0


def test_dense_exact_matches_dispatch_at_full_capacity():
    s, m, f, e = 48, 16, 24, 4
    x, logits, wg, wu, wd = _rand_moe(jax.random.PRNGKey(7), s, m, f, e)
    from llmlb_tpu.ops.moe import moe_dense_exact

    dispatch = moe_dispatch_combine(x, logits, wg, wu, wd, num_selected=2, capacity=s)
    dense = moe_dense_exact(x, logits, wg, wu, wd, num_selected=2)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(dispatch), rtol=1e-4, atol=1e-4
    )


def test_default_capacity():
    assert default_capacity(256, 8, 2) == 80  # 256*2/8*1.25
    assert default_capacity(4, 8, 1) >= 4


def test_mixtral_prefill_decode_consistency():
    """Prefill logits at position t == decode logits after feeding t tokens."""
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.models import mixtral

    cfg = get_preset("debug-moe-tiny")
    params = mixtral.init_params(cfg, jax.random.PRNGKey(3))
    b, t, cap = 2, 8, 16
    ids = jax.random.randint(jax.random.PRNGKey(4), (b, t), 0, cfg.vocab_size)
    lens = jnp.full((b,), t, jnp.int32)

    ck, cv = mixtral.init_kv_cache(cfg, b, cap)
    logits_p, ck, cv = mixtral.prefill(params, cfg, ids, lens, ck, cv)

    # replay: prefill t-1 tokens then decode the t-th
    ck2, cv2 = mixtral.init_kv_cache(cfg, b, cap)
    lens2 = jnp.full((b,), t - 1, jnp.int32)
    _, ck2, cv2 = mixtral.prefill(params, cfg, ids[:, : t - 1], lens2, ck2, cv2)
    logits_d, _, _ = mixtral.decode_step(
        params, cfg, ids[:, t - 1], lens2, ck2, cv2
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_d), rtol=5e-4, atol=5e-4
    )


def test_mixtral_ep_tp_sharded_serving_step(cpu_mesh_devices):
    """Full sharded Mixtral step on a dp=1 ep=4 tp=2 mesh == unsharded."""
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.models import mixtral

    cfg = get_preset("debug-moe-tiny")
    params = mixtral.init_params(cfg, jax.random.PRNGKey(5))
    mesh = build_mesh(MeshConfig(dp=1, sp=1, ep=4, tp=2), devices=cpu_mesh_devices)

    b, t, cap = 2, 8, 16
    ids = jax.random.randint(jax.random.PRNGKey(6), (b, t), 0, cfg.vocab_size)
    lens = jnp.full((b,), t, jnp.int32)

    ck, cv = mixtral.init_kv_cache(cfg, b, cap)
    want, _, _ = mixtral.prefill(params, cfg, ids, lens, ck, cv)

    shardings = mixtral.param_shardings(cfg, mesh)
    params_sh = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    ck, cv = mixtral.init_kv_cache(cfg, b, cap)
    ck_sh, cv_sh = mixtral.kv_cache_shardings(cfg, mesh)
    ck, cv = jax.device_put(ck, ck_sh), jax.device_put(cv, cv_sh)
    got, ck, cv = mixtral.prefill(params_sh, cfg, ids, lens, ck, cv, mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-4
    )

    # and one decode step on the same sharded state
    tok = jnp.argmax(got, -1).astype(jnp.int32)
    logits_d, _, _ = mixtral.decode_step(params_sh, cfg, tok, lens, ck, cv, mesh)
    assert np.isfinite(np.asarray(logits_d)).all()
