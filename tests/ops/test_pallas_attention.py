"""Pallas attention kernels vs the XLA einsum baselines (interpret mode on CPU).

Mirrors the reference's unit-tier strategy (SURVEY.md §4): pure-logic numeric
checks, no hardware dependency — `interpret=True` runs the same kernel the TPU
compiles, through the Pallas interpreter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.ops.attention import (
    gather_kv_pages,
    gqa_attention_decode,
    gqa_attention_extend,
    paged_attention_decode,
    paged_attention_extend,
    gqa_attention_prefill,
)
from llmlb_tpu.ops.pallas_attention import (
    flash_decode,
    flash_prefill,
    paged_flash_decode,
    paged_flash_extend,
)


@pytest.fixture(autouse=True)
def _pin_baseline_to_xla(monkeypatch):
    """On a 1-chip TPU host the baselines would auto-dispatch to Pallas and the
    comparisons would become pallas-vs-pallas; pin the expected path to XLA.
    (test_model_dispatch_pallas_matches_xla overrides this per-mode.)"""
    monkeypatch.setenv("LLMLB_TPU_ATTENTION", "xla")


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "b,h,kv,d,s,block_k",
    [
        (2, 8, 8, 32, 64, 32),  # MHA, multiple blocks
        (3, 8, 2, 16, 96, 32),  # GQA g=4, S divisible
        (2, 4, 1, 32, 40, 32),  # MQA, ragged last block (40 = 32 + 8)
        (1, 8, 4, 64, 128, 128),  # single block covers everything
    ],
)
def test_flash_decode_matches_xla(b, h, kv, d, s, block_k):
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    q = _rand(keys[0], (b, 1, h, d))
    k_cache = _rand(keys[1], (b, s, kv, d))
    v_cache = _rand(keys[2], (b, s, kv, d))
    kv_lens = jax.random.randint(keys[3], (b,), 1, s + 1, jnp.int32)

    expected = gqa_attention_decode(q, k_cache, v_cache, kv_lens)
    got = flash_decode(
        q[:, 0], k_cache, v_cache, kv_lens, block_k=block_k, interpret=True
    )
    np.testing.assert_allclose(got, expected[:, 0], rtol=2e-5, atol=2e-5)


def test_flash_decode_extreme_lens():
    """kv_len=1 (only first token valid) and kv_len=S (fully dense)."""
    b, h, kv, d, s = 2, 4, 2, 16, 48
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand(keys[0], (b, 1, h, d))
    k_cache = _rand(keys[1], (b, s, kv, d))
    v_cache = _rand(keys[2], (b, s, kv, d))
    kv_lens = jnp.array([1, s], jnp.int32)

    expected = gqa_attention_decode(q, k_cache, v_cache, kv_lens)
    got = flash_decode(
        q[:, 0], k_cache, v_cache, kv_lens, block_k=16, interpret=True
    )
    np.testing.assert_allclose(got, expected[:, 0], rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "b,t,h,kv,d,block_q,block_k",
    [
        (2, 64, 8, 8, 32, 32, 32),  # MHA
        (2, 64, 8, 2, 16, 16, 32),  # GQA g=4, blk_q != blk_k
        (1, 40, 4, 1, 32, 32, 32),  # MQA, ragged T
        (2, 128, 8, 4, 64, 128, 128),  # single q/k block
    ],
)
def test_flash_prefill_matches_xla(b, t, h, kv, d, block_q, block_k):
    keys = jax.random.split(jax.random.PRNGKey(2), 4)
    q = _rand(keys[0], (b, t, h, d))
    k = _rand(keys[1], (b, t, kv, d))
    v = _rand(keys[2], (b, t, kv, d))
    prompt_lens = jax.random.randint(keys[3], (b,), 1, t + 1, jnp.int32)

    expected = gqa_attention_prefill(q, k, v, prompt_lens)
    got = flash_prefill(
        q, k, v, prompt_lens, block_q=block_q, block_k=block_k, interpret=True
    )
    # Padding rows (t >= prompt_len) are ignored downstream; compare valid rows.
    lens = np.asarray(prompt_lens)
    for bi in range(b):
        np.testing.assert_allclose(
            got[bi, : lens[bi]],
            expected[bi, : lens[bi]],
            rtol=2e-5,
            atol=2e-5,
        )


def test_flash_prefill_full_lens_all_rows():
    """With prompt_lens == T every row must match, padding included."""
    b, t, h, kv, d = 2, 48, 4, 2, 32
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(keys[0], (b, t, h, d))
    k = _rand(keys[1], (b, t, kv, d))
    v = _rand(keys[2], (b, t, kv, d))
    prompt_lens = jnp.full((b,), t, jnp.int32)

    expected = gqa_attention_prefill(q, k, v, prompt_lens)
    got = flash_prefill(
        q, k, v, prompt_lens, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)


def _paged_fixture(key, b, h, kv, d, page_size, pages_per_seq):
    """Random pool + per-row block tables drawing DISTINCT scattered pages
    (the pool is larger than needed so the gather order matters)."""
    rng = np.random.default_rng(
        int(jax.random.randint(key, (), 0, 2**31 - 1)))
    num_pages = b * pages_per_seq * 2 + 1  # page 0 reserved (trash)
    k_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, kv, d)).astype(np.float32))
    v_pages = jnp.asarray(
        rng.normal(size=(num_pages, page_size, kv, d)).astype(np.float32))
    perm = rng.permutation(np.arange(1, num_pages))[: b * pages_per_seq]
    tables = jnp.asarray(perm.reshape(b, pages_per_seq).astype(np.int32))
    return k_pages, v_pages, tables


@pytest.mark.parametrize(
    "b,h,kv,d,page_size,pages_per_seq",
    [
        (2, 8, 8, 32, 16, 4),  # MHA
        (3, 8, 2, 16, 32, 3),  # GQA g=4
        (2, 4, 1, 32, 16, 2),  # MQA
    ],
)
def test_paged_flash_decode_matches_dense(b, h, kv, d, page_size,
                                          pages_per_seq):
    """The paged kernel gathering KV through the block table must equal the
    dense kernel over the materialized (gathered) cache."""
    keys = jax.random.split(jax.random.PRNGKey(10), 3)
    cap = page_size * pages_per_seq
    q = _rand(keys[0], (b, 1, h, d))
    k_pages, v_pages, tables = _paged_fixture(
        keys[1], b, h, kv, d, page_size, pages_per_seq)
    kv_lens = jax.random.randint(keys[2], (b,), 1, cap + 1, jnp.int32)

    k_cache = gather_kv_pages(k_pages, tables)
    v_cache = gather_kv_pages(v_pages, tables)
    expected = gqa_attention_decode(q, k_cache, v_cache, kv_lens)
    got = paged_flash_decode(
        q[:, 0], k_pages, v_pages, tables, kv_lens, interpret=True
    )
    np.testing.assert_allclose(got, expected[:, 0], rtol=2e-5, atol=2e-5)


def test_paged_flash_decode_page_window():
    """`pages` bounds the sweep exactly like flash_decode's `window`: rows
    within the swept pages are exact."""
    b, h, kv, d, ps, ppn = 2, 4, 2, 16, 16, 4
    keys = jax.random.split(jax.random.PRNGKey(11), 2)
    q = _rand(keys[0], (b, 1, h, d))
    k_pages, v_pages, tables = _paged_fixture(keys[1], b, h, kv, d, ps, ppn)
    kv_lens = jnp.array([ps * 2, ps + 3], jnp.int32)  # within 2 pages

    k_cache = gather_kv_pages(k_pages, tables[:, :2])
    v_cache = gather_kv_pages(v_pages, tables[:, :2])
    expected = gqa_attention_decode(q, k_cache, v_cache, kv_lens)
    got = paged_flash_decode(
        q[:, 0], k_pages, v_pages, tables, kv_lens, pages=2, interpret=True
    )
    np.testing.assert_allclose(got, expected[:, 0], rtol=2e-5, atol=2e-5)
    # the dispatcher derives the page count from a token window
    got2 = paged_attention_decode(
        q, k_pages, v_pages, tables, kv_lens, window=2 * ps
    )
    np.testing.assert_allclose(got2, expected, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "b,t,h,kv,d,page_size,pages_per_seq,block_q",
    [
        (2, 16, 8, 8, 32, 16, 4, 16),  # MHA
        (2, 8, 8, 2, 16, 32, 2, 4),  # GQA g=4, small q blocks
        (1, 12, 4, 1, 32, 16, 3, 8),  # MQA, ragged T
    ],
)
def test_paged_flash_extend_matches_dense(b, t, h, kv, d, page_size,
                                          pages_per_seq, block_q):
    keys = jax.random.split(jax.random.PRNGKey(12), 4)
    cap = page_size * pages_per_seq
    q = _rand(keys[0], (b, t, h, d))
    k_pages, v_pages, tables = _paged_fixture(
        keys[1], b, h, kv, d, page_size, pages_per_seq)
    start_pos = jax.random.randint(keys[2], (b,), 0, cap - t, jnp.int32)
    chunk_lens = jax.random.randint(keys[3], (b,), 1, t + 1, jnp.int32)
    q_positions = start_pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]

    k_cache = gather_kv_pages(k_pages, tables)
    v_cache = gather_kv_pages(v_pages, tables)
    expected = gqa_attention_extend(q, k_cache, v_cache, q_positions, None)
    got = paged_flash_extend(
        q, k_pages, v_pages, tables, start_pos, chunk_lens,
        block_q=block_q, interpret=True,
    )
    # Padding rows (t >= chunk_len) are ignored downstream; compare valid rows.
    lens = np.asarray(chunk_lens)
    for bi in range(b):
        np.testing.assert_allclose(
            got[bi, : lens[bi]], expected[bi, : lens[bi]],
            rtol=2e-5, atol=2e-5,
        )
    # the XLA dispatcher path must agree everywhere (it has no padding skip)
    got2 = paged_attention_extend(
        q, k_pages, v_pages, tables, q_positions, chunk_lens
    )
    np.testing.assert_allclose(got2, expected, rtol=2e-5, atol=2e-5)


def test_model_dispatch_pallas_matches_xla(monkeypatch):
    """Full model prefill+decode with LLMLB_TPU_ATTENTION=pallas vs =xla.

    Uses shapes unique to this test: the jit cache is keyed on shapes/config,
    and the dispatch env var is read at trace time.
    """
    import numpy as np

    from llmlb_tpu.models.llama import (
        LlamaConfig,
        decode_step,
        init_kv_cache,
        init_params,
        prefill,
    )

    cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=96,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(7))
    batch, seq, capacity = 3, 24, 48
    ids = jax.random.randint(jax.random.PRNGKey(8), (batch, seq), 0, 128)
    lens = jnp.array([24, 10, 17], jnp.int32)

    results = {}
    for mode in ("xla", "pallas"):
        monkeypatch.setenv("LLMLB_TPU_ATTENTION", mode)
        prefill._clear_cache()
        decode_step._clear_cache()
        ck, cv = init_kv_cache(cfg, batch, capacity)
        logits, ck, cv = prefill(params, cfg, ids, lens, ck, cv)
        toks = jnp.argmax(logits, -1).astype(jnp.int32)
        logits2, ck, cv = decode_step(params, cfg, toks, lens, ck, cv)
        results[mode] = (np.asarray(logits), np.asarray(logits2))

    np.testing.assert_allclose(
        results["pallas"][0], results["xla"][0], rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        results["pallas"][1], results["xla"][1], rtol=1e-4, atol=1e-4
    )


def test_flash_extend_matches_xla_extend():
    """Chunked-prefill kernel vs the XLA einsum baseline, ragged starts."""
    import numpy as np

    from llmlb_tpu.ops.attention import gqa_attention_extend
    from llmlb_tpu.ops.pallas_attention import flash_extend

    rng = np.random.default_rng(5)
    b, t, h, k, d, s = 2, 16, 8, 4, 32, 64
    q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((b, s, k, d)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((b, s, k, d)), jnp.float32)
    starts = jnp.asarray([0, 23], jnp.int32)
    chunk_lens = jnp.asarray([t, 9], jnp.int32)
    positions = starts[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]

    ref = gqa_attention_extend(q, kc, vc, positions)  # XLA path (no lens)
    out = flash_extend(q, kc, vc, starts, chunk_lens, interpret=True,
                       block_q=8, block_k=16)
    # compare only valid queries; padded rows are ignored by the caller
    for bi in range(b):
        n = int(chunk_lens[bi])
        np.testing.assert_allclose(
            np.asarray(out)[bi, :n], np.asarray(ref)[bi, :n],
            rtol=2e-5, atol=2e-5,
        )


def test_flash_decode_window_bounds_sweep():
    """A static window >= max(kv_lens) must be a numeric no-op while sweeping
    fewer kv blocks (the scheduler's context-window bucket optimization)."""
    b, h, kv, d, s = 2, 8, 4, 32, 128
    keys = jax.random.split(jax.random.PRNGKey(2), 4)
    q = _rand(keys[0], (b, 1, h, d))
    k_cache = _rand(keys[1], (b, s, kv, d))
    v_cache = _rand(keys[2], (b, s, kv, d))
    kv_lens = jnp.array([40, 64], jnp.int32)  # all within window=64

    full = flash_decode(q[:, 0], k_cache, v_cache, kv_lens,
                        block_k=32, interpret=True)
    windowed = flash_decode(q[:, 0], k_cache, v_cache, kv_lens,
                            block_k=32, interpret=True, window=64)
    np.testing.assert_allclose(windowed, full, rtol=2e-5, atol=2e-5)

    # the XLA dispatch path with a window must also match
    xla_windowed = gqa_attention_decode(
        q, k_cache, v_cache, kv_lens, window=64
    )
    np.testing.assert_allclose(
        xla_windowed[:, 0], full, rtol=2e-5, atol=2e-5
    )
