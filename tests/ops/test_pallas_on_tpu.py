"""On-hardware Pallas smoke tests (VERDICT r1 item 2).

These run the flash kernels NON-interpreted — a real Mosaic compile + execute
on the TPU — and compare against the XLA einsum baselines. Skipped anywhere
but a live TPU backend; the interpret-mode numerics live in
test_pallas_attention.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.ops.attention import gqa_attention_decode, gqa_attention_prefill
from llmlb_tpu.ops.pallas_attention import flash_decode, flash_prefill

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="requires a live TPU backend (Mosaic compile)",
)


@pytest.fixture(autouse=True)
def _pin_baseline_to_xla(monkeypatch):
    monkeypatch.setenv("LLMLB_TPU_ATTENTION", "xla")


def _rand(key, shape, dtype=jnp.bfloat16):
    return jax.random.normal(key, shape, jnp.float32).astype(dtype)


def test_flash_decode_compiles_and_matches_on_tpu():
    b, h, kv, d, s = 8, 32, 4, 64, 1024  # tinyllama-1.1b serving shape
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    q = _rand(keys[0], (b, 1, h, d))
    k_cache = _rand(keys[1], (b, s, kv, d))
    v_cache = _rand(keys[2], (b, s, kv, d))
    kv_lens = jax.random.randint(keys[3], (b,), 1, s + 1, jnp.int32)

    expected = gqa_attention_decode(q, k_cache, v_cache, kv_lens)
    got = flash_decode(q[:, 0], k_cache, v_cache, kv_lens, interpret=False)
    got.block_until_ready()  # force the Mosaic executable to actually run
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected[:, 0], np.float32),
        rtol=2e-2, atol=2e-2,  # bf16 inputs, fp32 accumulation
    )


def test_flash_prefill_compiles_and_matches_on_tpu():
    b, t, h, kv, d = 2, 512, 32, 4, 64
    keys = jax.random.split(jax.random.PRNGKey(1), 4)
    q = _rand(keys[0], (b, t, h, d))
    k = _rand(keys[1], (b, t, kv, d))
    v = _rand(keys[2], (b, t, kv, d))
    prompt_lens = jnp.asarray([t, t // 2 + 3], jnp.int32)

    expected = gqa_attention_prefill(q, k, v, prompt_lens)
    got = flash_prefill(q, k, v, prompt_lens, interpret=False)
    got.block_until_ready()
    # compare only valid tokens (padding rows are don't-care)
    for i, n in enumerate(np.asarray(prompt_lens)):
        np.testing.assert_allclose(
            np.asarray(got[i, :n], np.float32),
            np.asarray(expected[i, :n], np.float32),
            rtol=2e-2, atol=2e-2,
        )


def test_engine_decode_through_pallas_on_tpu(monkeypatch):
    """The serving dispatch (ops/attention.py) must run Pallas kernels through
    a real model prefill + decode step and produce finite logits.

    Uses a config whose shapes no other test shares: jax.jit caches
    executables keyed on shapes + static cfg (not the env var), so a unique
    cfg guarantees this test really traces — and therefore Mosaic-compiles —
    the Pallas path rather than reusing a cached XLA executable.
    """
    from llmlb_tpu.models import llama
    from llmlb_tpu.models.llama import LlamaConfig

    monkeypatch.setenv("LLMLB_TPU_ATTENTION", "pallas")
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=96, intermediate_size=192,
        num_layers=2, num_heads=6, num_kv_heads=2, dtype=jnp.float32,
        max_position_embeddings=256,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ck, cv = llama.init_kv_cache(cfg, 3, 48)
    ids = jnp.zeros((3, 24), jnp.int32)
    lens = jnp.asarray([5, 9, 24], jnp.int32)
    logits, ck, cv = llama.prefill(params, cfg, ids, lens, ck, cv)
    logits2, _, _ = llama.decode_step(
        params, cfg, jnp.asarray([1, 2, 3], jnp.int32), lens, ck, cv
    )
    assert np.isfinite(np.asarray(logits)).all()
    assert np.isfinite(np.asarray(logits2)).all()


def test_flash_extend_compiles_and_matches_on_tpu():
    """Chunked-prefill kernel Mosaic-compiled against the XLA baseline."""
    from llmlb_tpu.ops.attention import gqa_attention_extend
    from llmlb_tpu.ops.pallas_attention import flash_extend

    b, t, h, kv, d, s = 2, 256, 32, 4, 64, 1024
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q = _rand(keys[0], (b, t, h, d))
    k_cache = _rand(keys[1], (b, s, kv, d))
    v_cache = _rand(keys[2], (b, s, kv, d))
    starts = jnp.asarray([0, 512], jnp.int32)
    chunk_lens = jnp.asarray([t, 200], jnp.int32)
    positions = starts[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]

    expected = gqa_attention_extend(q, k_cache, v_cache, positions)
    got = flash_extend(q, k_cache, v_cache, starts, chunk_lens,
                       interpret=False)
    got.block_until_ready()
    for bi in range(b):
        n = int(chunk_lens[bi])
        np.testing.assert_allclose(
            np.asarray(got, np.float32)[bi, :n],
            np.asarray(expected, np.float32)[bi, :n],
            rtol=2e-2, atol=2e-2,
        )
