"""Int8-paged attention parity: the quantized pool read paths (XLA gather
fallback AND Pallas interpret-mode kernels) must track the bf16 baseline
within the docs/quantization.md tolerance on unit-variance inputs."""

import numpy as np
import jax.numpy as jnp
import pytest

from llmlb_tpu.ops.attention import (
    gather_kv_pages,
    paged_attention_decode,
    paged_attention_extend,
)
from llmlb_tpu.ops.pallas_attention import (
    paged_flash_decode,
    paged_flash_decode_quant,
    paged_flash_extend,
    paged_flash_extend_quant,
)
from llmlb_tpu.quant import quantize_kv

B, H, K, D, P, PS, PPN = 2, 8, 4, 16, 9, 8, 4
TOL = 0.05


def _pools(seed=0):
    rng = np.random.default_rng(seed)
    k_pages = rng.normal(size=(P, PS, K, D)).astype(np.float32)
    v_pages = rng.normal(size=(P, PS, K, D)).astype(np.float32)
    kq, ks = quantize_kv(k_pages)
    vq, vs = quantize_kv(v_pages)
    tables = np.array([[1, 2, 3, 0], [4, 5, 6, 0]], np.int32)
    return (jnp.asarray(k_pages), jnp.asarray(v_pages),
            {"q": jnp.asarray(kq), "s": jnp.asarray(ks)},
            {"q": jnp.asarray(vq), "s": jnp.asarray(vs)},
            jnp.asarray(tables), rng)


def test_gather_kv_pages_dequantizes():
    k_pages, _, qk, _, tables, _ = _pools()
    dense = gather_kv_pages(k_pages, tables)
    deq = gather_kv_pages(qk, tables)
    assert deq.dtype == jnp.bfloat16
    assert np.abs(np.asarray(deq, np.float32)
                  - np.asarray(dense)).max() < TOL


def test_paged_decode_xla_parity():
    k_pages, v_pages, qk, qv, tables, rng = _pools(1)
    q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
    kv_lens = jnp.asarray([PS * 3, PS * 2], jnp.int32)
    base = paged_attention_decode(q, k_pages, v_pages, tables, kv_lens)
    quant = paged_attention_decode(q, qk, qv, tables, kv_lens)
    assert np.abs(np.asarray(base) - np.asarray(quant,
                                                np.float32)).max() < TOL


def test_paged_extend_xla_parity():
    k_pages, v_pages, qk, qv, tables, rng = _pools(2)
    t = 4
    q = jnp.asarray(rng.normal(size=(B, t, H, D)), jnp.float32)
    start = jnp.asarray([8, 4], jnp.int32)
    positions = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    lens = jnp.asarray([t, t - 1], jnp.int32)
    base = paged_attention_extend(q, k_pages, v_pages, tables, positions,
                                  lens)
    quant = paged_attention_extend(q, qk, qv, tables, positions, lens)
    assert np.abs(np.asarray(base) - np.asarray(quant,
                                                np.float32)).max() < TOL


def test_paged_flash_decode_quant_interpret_parity():
    """Interpret-mode kernel vs both the bf16 kernel (tolerance) and the
    XLA dequant route (the two quantized paths read identical cells)."""
    k_pages, v_pages, qk, qv, tables, rng = _pools(3)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kv_lens = jnp.asarray([PS * 3 - 2, PS + 3], jnp.int32)
    base = paged_flash_decode(q, k_pages, v_pages, tables, kv_lens,
                              interpret=True)
    quant = paged_flash_decode_quant(
        q, qk["q"], qk["s"], qv["q"], qv["s"], tables, kv_lens,
        interpret=True,
    )
    assert np.abs(np.asarray(base) - np.asarray(quant)).max() < TOL

    # both quantized routes dequant to q.dtype before the dots, so they
    # differ only by online- vs plain-softmax accumulation order
    xla = paged_attention_decode(q[:, None], qk, qv, tables, kv_lens)[:, 0]
    assert np.abs(np.asarray(quant)
                  - np.asarray(xla, np.float32)).max() < 2e-3


def test_paged_flash_decode_quant_respects_pages_window():
    """Rows within the swept pages stay exact when the sweep is bounded —
    the dequant variant must keep flash_decode's window contract."""
    k_pages, v_pages, qk, qv, tables, rng = _pools(4)
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    kv_lens = jnp.asarray([PS * 2, PS], jnp.int32)  # within 2 pages
    full = paged_flash_decode_quant(
        q, qk["q"], qk["s"], qv["q"], qv["s"], tables, kv_lens,
        interpret=True,
    )
    windowed = paged_flash_decode_quant(
        q, qk["q"], qk["s"], qv["q"], qv["s"], tables, kv_lens, pages=2,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(full), np.asarray(windowed),
                               atol=1e-6)


def test_paged_flash_extend_quant_interpret_parity():
    k_pages, v_pages, qk, qv, tables, rng = _pools(5)
    t = 6
    q = jnp.asarray(rng.normal(size=(B, t, H, D)), jnp.float32)
    start = jnp.asarray([10, 2], jnp.int32)
    lens = jnp.asarray([t, t - 2], jnp.int32)
    base = paged_flash_extend(q, k_pages, v_pages, tables, start, lens,
                              interpret=True)
    quant = paged_flash_extend_quant(
        q, qk["q"], qk["s"], qv["q"], qv["s"], tables, start, lens,
        interpret=True,
    )
    # padding rows past chunk_lens are garbage in both — compare valid rows
    for b, n in enumerate([t, t - 2]):
        assert np.abs(np.asarray(base)[b, :n]
                      - np.asarray(quant)[b, :n]).max() < TOL


@pytest.mark.parametrize("route", ["decode", "extend"])
def test_quantized_pool_means_quantized_kernel(route, monkeypatch):
    """The dispatcher must route {"q","s"} pools to the quant kernels when
    Pallas is enabled — mixing an int8 pool into the bf16 kernel would be
    garbage, not an error."""
    import llmlb_tpu.ops.attention as attn

    monkeypatch.setenv("LLMLB_TPU_ATTENTION", "pallas")
    k_pages, v_pages, qk, qv, tables, rng = _pools(6)
    if route == "decode":
        q = jnp.asarray(rng.normal(size=(B, 1, H, D)), jnp.float32)
        kv_lens = jnp.asarray([PS, PS], jnp.int32)
        out = attn.paged_attention_decode(q, qk, qv, tables, kv_lens)
        ref = attn.paged_attention_decode(q, k_pages, v_pages, tables,
                                          kv_lens)
    else:
        q = jnp.asarray(rng.normal(size=(B, 3, H, D)), jnp.float32)
        positions = jnp.asarray([[8, 9, 10], [4, 5, 6]], jnp.int32)
        lens = jnp.asarray([3, 3], jnp.int32)
        out = attn.paged_attention_extend(q, qk, qv, tables, positions,
                                          lens)
        ref = attn.paged_attention_extend(q, k_pages, v_pages, tables,
                                          positions, lens)
    assert np.abs(np.asarray(out, np.float32)
                  - np.asarray(ref, np.float32)).max() < TOL
