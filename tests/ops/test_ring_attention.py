"""Ring attention (sequence-parallel prefill) vs the dense XLA reference.

Strategy per SURVEY.md §4: multi-device behavior tested on the virtual 8-device
CPU mesh — the ring (shard_map + ppermute) path must match dense causal GQA
attention and the dense full-model prefill bit-for-bit up to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.ops.attention import gqa_attention_prefill
from llmlb_tpu.ops.ring_attention import ring_prefill_attention
from llmlb_tpu.parallel.mesh import MeshConfig, build_mesh


def _rand_qkv(key, b, t, h, kh, d):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, kh, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, kh, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense_mha(sp, cpu_mesh_devices):
    mesh = build_mesh(MeshConfig(dp=1, sp=sp, tp=1), devices=cpu_mesh_devices[:sp])
    b, t, h, d = 2, 64, 4, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), b, t, h, h, d)
    lens = jnp.array([64, 37], jnp.int32)  # one full, one ragged (not chunk-aligned)

    dense = gqa_attention_prefill(q, k, v, lens)
    ring = ring_prefill_attention(q, k, v, lens, mesh)
    valid = np.arange(t)[None, :, None, None] < np.asarray(lens)[:, None, None, None]
    np.testing.assert_allclose(
        np.where(valid, np.asarray(ring), 0.0),
        np.where(valid, np.asarray(dense), 0.0),
        rtol=2e-5, atol=2e-5,
    )


def test_ring_matches_dense_gqa_with_tp(cpu_mesh_devices):
    """GQA (h=8 over kh=4) with heads tp-sharded and sequence sp-sharded."""
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=2), devices=cpu_mesh_devices)
    b, t, h, kh, d = 2, 32, 8, 4, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b, t, h, kh, d)
    lens = jnp.array([32, 9], jnp.int32)

    dense = gqa_attention_prefill(q, k, v, lens)
    ring = ring_prefill_attention(q, k, v, lens, mesh)
    valid = np.arange(t)[None, :, None, None] < np.asarray(lens)[:, None, None, None]
    np.testing.assert_allclose(
        np.where(valid, np.asarray(ring), 0.0),
        np.where(valid, np.asarray(dense), 0.0),
        rtol=2e-5, atol=2e-5,
    )


def test_ring_with_dp_batch_sharding(cpu_mesh_devices):
    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2), devices=cpu_mesh_devices)
    b, t, h, kh, d = 4, 16, 4, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b, t, h, kh, d)
    lens = jnp.array([16, 11, 3, 16], jnp.int32)

    dense = gqa_attention_prefill(q, k, v, lens)
    ring = ring_prefill_attention(q, k, v, lens, mesh)
    valid = np.arange(t)[None, :, None, None] < np.asarray(lens)[:, None, None, None]
    np.testing.assert_allclose(
        np.where(valid, np.asarray(ring), 0.0),
        np.where(valid, np.asarray(dense), 0.0),
        rtol=2e-5, atol=2e-5,
    )


def test_context_parallel_prefill_matches_dense(cpu_mesh_devices):
    """Full-model sequence-parallel prefill == dense prefill (logits and KV)."""
    from llmlb_tpu.engine.presets import get_preset
    from llmlb_tpu.models.llama import (
        init_kv_cache, init_params, make_context_parallel_prefill, prefill,
    )

    cfg = get_preset("debug-tiny")
    params = init_params(cfg, jax.random.PRNGKey(3))
    mesh = build_mesh(MeshConfig(dp=1, sp=4, tp=2), devices=cpu_mesh_devices)

    b, t = 2, 32
    ids = jax.random.randint(jax.random.PRNGKey(4), (b, t), 0, cfg.vocab_size)
    lens = jnp.array([32, 21], jnp.int32)

    cache_k, cache_v = init_kv_cache(cfg, b, t)
    dense_logits, dense_k, dense_v = prefill(
        params, cfg, ids, lens, cache_k, cache_v
    )

    cp_prefill = make_context_parallel_prefill(cfg, mesh)
    cp_logits, k_all, v_all = cp_prefill(params, ids, lens)

    np.testing.assert_allclose(
        np.asarray(cp_logits), np.asarray(dense_logits), rtol=2e-4, atol=2e-4
    )
    # KV written during dense prefill == KV returned by the cp path ([L,B,T,K,D])
    valid = np.arange(t)[None, None, :, None, None] < np.asarray(lens)[None, :, None, None, None]
    np.testing.assert_allclose(
        np.where(valid, np.asarray(k_all), 0.0),
        np.where(valid, np.asarray(dense_k), 0.0),
        rtol=2e-5, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.where(valid, np.asarray(v_all), 0.0),
        np.where(valid, np.asarray(dense_v), 0.0),
        rtol=2e-5, atol=2e-5,
    )


def test_mesh_config_sp_resolution():
    cfg = MeshConfig(dp=2, tp=-1, sp=2).resolve(8)
    assert (cfg.dp, cfg.sp, cfg.tp) == (2, 2, 2)
    cfg = MeshConfig(dp=1, tp=1, sp=-1).resolve(8)
    assert cfg.sp == 8
    with pytest.raises(ValueError):
        MeshConfig(dp=3, tp=-1, sp=1).resolve(8)
