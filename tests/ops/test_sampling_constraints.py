"""Sampling-parity tests for grammar masks and per-request seeds.

The load-bearing property: the constraint mask is applied to the FULL logits
BEFORE the TOPK_PREFILTER=64 top-k prefilter. An adversarial distribution
whose allowed token set lies entirely outside the unconstrained top-64 must
still sample only allowed ids — masking after the prefilter would leave the
candidate window all -inf."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmlb_tpu.engine.tokenizer import ByteTokenizer
from llmlb_tpu.ops.sampling import TOPK_PREFILTER, sample_tokens
from llmlb_tpu.structured import ConstraintCompiler, ConstraintState

VOCAB = 512


def _adversarial_logits(allowed: np.ndarray) -> np.ndarray:
    """[1, V] logits whose top-TOPK_PREFILTER ids are all DISALLOWED."""
    rng = np.random.default_rng(0)
    logits = rng.normal(0.0, 0.1, size=(1, VOCAB)).astype(np.float32)
    blocked = np.nonzero(~allowed)[0]
    assert len(blocked) >= TOPK_PREFILTER
    logits[0, blocked[:TOPK_PREFILTER]] += 100.0  # decoys dominate
    logits[0, allowed] -= 10.0  # allowed set buried far below the window
    top = np.argsort(logits[0])[::-1][:TOPK_PREFILTER]
    assert not allowed[top].any(), "construction failed: allowed id in top-64"
    return logits


@pytest.fixture(scope="module")
def int_constraint():
    compiler = ConstraintCompiler(ByteTokenizer(VOCAB), VOCAB)
    return compiler.compile_spec({"type": "regex", "pattern": r"-?[0-9]+"})


def test_mask_applied_before_topk_prefilter_greedy(int_constraint):
    state = ConstraintState(int_constraint)
    allowed = int_constraint.allowed[state.state]
    logits = jnp.asarray(_adversarial_logits(allowed))
    bias = jnp.asarray(state.bias_row())[None, :]
    ids = sample_tokens(
        logits, jax.random.PRNGKey(0),
        jnp.zeros((1,)), jnp.ones((1,)), jnp.zeros((1,), jnp.int32),
        bias,
    )
    assert allowed[int(ids[0])], int(ids[0])


def test_mask_applied_before_topk_prefilter_stochastic(int_constraint):
    state = ConstraintState(int_constraint)
    allowed = int_constraint.allowed[state.state]
    logits = jnp.asarray(_adversarial_logits(allowed))
    bias = jnp.asarray(state.bias_row())[None, :]
    for step in range(32):
        ids = sample_tokens(
            logits, jax.random.PRNGKey(step),
            jnp.ones((1,)), jnp.ones((1,)) * 0.95,
            jnp.zeros((1,), jnp.int32), bias,
        )
        assert allowed[int(ids[0])], int(ids[0])


def test_mask_batch_mixes_constrained_and_free_rows(int_constraint):
    """[B, V] mask: row 0 constrained, row 1 free — the free row must keep
    the unconstrained argmax, bit for bit."""
    state = ConstraintState(int_constraint)
    allowed = int_constraint.allowed[state.state]
    adversarial = _adversarial_logits(allowed)
    logits = jnp.asarray(np.vstack([adversarial, adversarial]))
    bias = jnp.asarray(np.vstack([
        state.bias_row(), np.zeros((VOCAB,), np.float32)
    ]))
    temps = jnp.zeros((2,))
    ids = sample_tokens(
        logits, jax.random.PRNGKey(0), temps, jnp.ones((2,)),
        jnp.zeros((2,), jnp.int32), bias,
    )
    assert allowed[int(ids[0])]
    assert int(ids[1]) == int(jnp.argmax(logits[1]))


def test_no_mask_no_seeds_is_bit_identical_to_legacy_signature():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, VOCAB)).astype(np.float32))
    key = jax.random.PRNGKey(7)
    temps = jnp.asarray([0.0, 0.7, 1.0, 1.3])
    top_ps = jnp.asarray([1.0, 0.9, 0.95, 1.0])
    top_ks = jnp.asarray([0, 5, 0, 40], jnp.int32)
    legacy = sample_tokens(logits, key, temps, top_ps, top_ks)
    # seeds=-1 rows must take the shared-key path unchanged
    seeds = jnp.full((4,), -1, jnp.int32)
    steps = jnp.asarray([3, 9, 2, 7], jnp.int32)
    new = sample_tokens(logits, key, temps, top_ps, top_ks, None, seeds, steps)
    np.testing.assert_array_equal(np.asarray(legacy), np.asarray(new))


def test_seeded_rows_reproduce_independent_of_batch_and_key():
    rng = np.random.default_rng(2)
    row = rng.normal(size=(VOCAB,)).astype(np.float32)
    temps1 = jnp.ones((1,))
    ids_a = sample_tokens(
        jnp.asarray(row[None, :]), jax.random.PRNGKey(0), temps1,
        jnp.ones((1,)), jnp.zeros((1,), jnp.int32), None,
        jnp.asarray([99], jnp.int32), jnp.asarray([5], jnp.int32),
    )
    # different shared key, different batch position, same (seed, step, row)
    batch = np.vstack([rng.normal(size=(VOCAB,)).astype(np.float32), row])
    ids_b = sample_tokens(
        jnp.asarray(batch), jax.random.PRNGKey(1234), jnp.ones((2,)),
        jnp.ones((2,)), jnp.zeros((2,), jnp.int32), None,
        jnp.asarray([-1, 99], jnp.int32), jnp.asarray([0, 5], jnp.int32),
    )
    assert int(ids_a[0]) == int(ids_b[1])
    # a different step must be able to move the sample over many draws
    draws = {
        int(sample_tokens(
            jnp.asarray(row[None, :]), jax.random.PRNGKey(0), temps1,
            jnp.ones((1,)), jnp.zeros((1,), jnp.int32), None,
            jnp.asarray([99], jnp.int32), jnp.asarray([s], jnp.int32),
        )[0])
        for s in range(16)
    }
    assert len(draws) > 1


def test_seeded_greedy_ignores_seed():
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(1, VOCAB)).astype(np.float32))
    ids = sample_tokens(
        logits, jax.random.PRNGKey(0), jnp.zeros((1,)), jnp.ones((1,)),
        jnp.zeros((1,), jnp.int32), None,
        jnp.asarray([5], jnp.int32), jnp.asarray([0], jnp.int32),
    )
    assert int(ids[0]) == int(jnp.argmax(logits[0]))
