"""Quantization primitives: scale correctness, round-trip error bounds,
pytree quantization, KV vector round trips, knob parsing, byte math.
Tolerances follow docs/quantization.md's error model (per-element error
<= scale/2 = group absmax / 254)."""

import numpy as np
import pytest

from llmlb_tpu.quant import (
    WEIGHT_QUANT_NAMES,
    dequantize_channelwise,
    dequantize_kv,
    kv_cell_bytes,
    parse_quant_mode,
    quantize_channelwise,
    quantize_kv,
    quantize_params,
)

# ------------------------------------------------------------------ weights


def test_channelwise_scale_is_per_output_channel():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 16)).astype(np.float32)  # [in, out]
    q, scale = quantize_channelwise(w)
    assert q.shape == w.shape and q.dtype == np.int8
    assert scale.shape == (16,) and scale.dtype == np.float32
    # scale is the column absmax / 127 — per OUTPUT channel
    np.testing.assert_allclose(scale, np.abs(w).max(axis=0) / 127.0,
                               rtol=1e-6)
    # the absmax element of every column quantizes to ±127 exactly
    assert (np.abs(q).max(axis=0) == 127).all()


def test_channelwise_round_trip_error_bound():
    rng = np.random.default_rng(1)
    w = (rng.normal(size=(4, 32, 64)) * rng.uniform(0.1, 10)).astype(
        np.float32
    )  # stacked [L, in, out]
    q, scale = quantize_channelwise(w)
    back = dequantize_channelwise(q, scale)
    # per-element error <= scale/2 (round-to-nearest), i.e. absmax/254
    bound = np.abs(w).max(axis=1, keepdims=True) / 253.0
    assert (np.abs(back - w) <= bound + 1e-7).all()


def test_channelwise_matmul_scale_commutes():
    """The serving matmul applies the scale to the OUTPUT; that must equal
    dequantizing the weight first (the scale is constant along the
    contraction)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    w = rng.normal(size=(8, 12)).astype(np.float32)
    q, scale = quantize_channelwise(w)
    via_output = (x @ q.astype(np.float32)) * scale
    via_weight = x @ dequantize_channelwise(q, scale)
    np.testing.assert_allclose(via_output, via_weight, rtol=1e-6)


def test_all_zero_channel_quantizes_to_zero():
    w = np.zeros((4, 4), np.float32)
    q, scale = quantize_channelwise(w)
    assert (q == 0).all() and (scale > 0).all()
    assert (dequantize_channelwise(q, scale) == 0).all()


def test_quantize_params_is_idempotent_and_selective():
    rng = np.random.default_rng(3)
    params = {
        "wq": rng.normal(size=(2, 8, 8)).astype(np.float32),
        "embed": rng.normal(size=(16, 8)).astype(np.float32),
        "ln_attn": np.ones((2, 8), np.float32),
    }
    out = quantize_params(params)
    assert out["wq"].dtype == np.int8 and "wq_scale" in out
    # embeddings/norms stay untouched
    assert out["embed"] is params["embed"]
    assert out["ln_attn"] is params["ln_attn"]
    assert "embed_scale" not in out and "ln_attn_scale" not in out
    # second pass is a no-op (same arrays, no double quantization)
    again = quantize_params(out)
    assert again["wq"] is out["wq"]
    assert again["wq_scale"] is out["wq_scale"]


def test_quantize_params_covers_both_families():
    assert {"wq", "wk", "wv", "wo", "wg", "wu", "wd"} <= set(
        WEIGHT_QUANT_NAMES
    )
    assert {"we_gate", "we_up", "we_down"} <= set(WEIGHT_QUANT_NAMES)


# ----------------------------------------------------------------------- KV


def test_kv_round_trip_error_bound():
    rng = np.random.default_rng(4)
    kv = (rng.normal(size=(3, 5, 4, 16)) * 3).astype(np.float32)
    q, scale = quantize_kv(kv)
    assert q.shape == kv.shape and q.dtype == np.int8
    assert scale.shape == kv.shape[:-1] and scale.dtype == np.float32
    back = dequantize_kv(q, scale, np.float32)
    bound = np.abs(kv).max(axis=-1, keepdims=True) / 253.0
    assert (np.abs(back - kv) <= bound + 1e-7).all()


def test_kv_scale_is_per_vector():
    kv = np.stack([np.full((8,), 2.0), np.full((8,), 0.5)]).astype(
        np.float32
    )
    _, scale = quantize_kv(kv)
    np.testing.assert_allclose(scale, [2.0 / 127, 0.5 / 127], rtol=1e-6)


def test_kv_quantize_works_under_jit():
    import jax
    import jax.numpy as jnp

    kv = jnp.asarray(np.random.default_rng(5).normal(size=(2, 4, 8)),
                     jnp.float32)
    q, scale = jax.jit(quantize_kv)(kv)
    back = dequantize_kv(np.asarray(q), np.asarray(scale), np.float32)
    assert np.abs(back - np.asarray(kv)).max() < 0.05


# -------------------------------------------------------------------- knobs


@pytest.mark.parametrize("mode,weights,kv", [
    (None, False, False), ("off", False, False), ("0", False, False),
    ("weights", True, False), ("kv", False, True), ("all", True, True),
    ("ALL", True, True),
])
def test_parse_quant_mode(mode, weights, kv, monkeypatch):
    monkeypatch.delenv("LLMLB_QUANTIZE", raising=False)
    qc = parse_quant_mode(mode)
    assert (qc.weights, qc.kv) == (weights, kv)


def test_parse_quant_mode_env_fallback(monkeypatch):
    monkeypatch.setenv("LLMLB_QUANTIZE", "kv")
    assert parse_quant_mode(None).mode == "kv"
    monkeypatch.delenv("LLMLB_QUANTIZE")
    assert parse_quant_mode(None).mode == "off"


def test_parse_quant_mode_rejects_typos():
    with pytest.raises(ValueError):
        parse_quant_mode("int8")  # must not silently serve bf16


def test_kv_cell_bytes():
    # bf16: D*2; int8: D*1 + one f32 scale
    assert kv_cell_bytes(64, False, 2) == 128
    assert kv_cell_bytes(64, True, 2) == 68
    assert kv_cell_bytes(128, True, 2) == 132
