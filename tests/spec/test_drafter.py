"""PromptLookupDrafter units: suffix matching, incremental updates, and the
no-self-match property the one-behind indexing scheme guarantees."""

import pytest

from llmlb_tpu.spec import PromptLookupDrafter, SpecConfig


def test_prompt_repeat_is_proposed():
    # tail (1,2,3) occurred earlier at positions 0..2; continuation is 4,1,2,3
    d = PromptLookupDrafter([1, 2, 3, 4, 1, 2, 3], max_ngram=3)
    assert d.propose(4) == [4, 1, 2, 3]
    assert d.propose(2) == [4, 1]


def test_no_match_returns_empty():
    d = PromptLookupDrafter([1, 2, 3, 4, 5], max_ngram=3)
    assert d.propose(4) == []  # tail (3,4,5) / (4,5) / (5) never recurred


def test_longest_ngram_wins():
    # tail (7, 8): the 2-gram match at [7, 8, 9] must beat the 1-gram (8)
    # match elsewhere — longer context, better continuation
    d = PromptLookupDrafter([7, 8, 9, 8, 1, 7, 8], max_ngram=3)
    assert d.propose(1) == [9]


def test_most_recent_occurrence_wins():
    # (5,) occurred twice; the LATER occurrence's continuation is proposed
    d = PromptLookupDrafter([5, 1, 5, 2, 5], max_ngram=1)
    assert d.propose(1) == [2]


def test_tail_never_matches_itself():
    # a repeated tail must find the EARLIER occurrence, not its own position
    d = PromptLookupDrafter([3, 3], max_ngram=1)
    assert d.propose(2) == [3]  # follows position 1 (after the first 3)
    d2 = PromptLookupDrafter([9], max_ngram=1)
    assert d2.propose(3) == []  # single occurrence: nothing earlier


def test_incremental_append_extends_the_index():
    d = PromptLookupDrafter([1, 2, 3], max_ngram=2)
    assert d.propose(2) == []
    for t in (9, 1, 2):  # generated tokens re-create the (1, 2) bigram tail
        d.append(t)
    assert d.propose(2) == [3, 9]
    assert len(d) == 6


def test_proposal_truncates_at_sequence_end():
    d = PromptLookupDrafter([4, 5, 4, 5], max_ngram=2)
    # tail (4,5) matched at positions 0..1 -> continuation [4, 5] then ends
    assert d.propose(8) == [4, 5]


def test_spec_config_validation():
    with pytest.raises(ValueError):
        SpecConfig(max_draft_tokens=0)
    with pytest.raises(ValueError):
        SpecConfig(min_ngram=3, max_ngram=2)
    cfg = SpecConfig(enabled=True, max_draft_tokens=8, max_ngram=4)
    assert cfg.min_ngram == 1 and cfg.max_draft_tokens == 8
