"""Unit tests for the structured-output subsystem (llmlb_tpu/structured):
regex→DFA engine, JSON-Schema→regex compiler, token-level mask tables,
ConstraintState advancement, and the LRU compile cache."""

import json

import jsonschema
import numpy as np
import pytest

from llmlb_tpu.engine.tokenizer import ByteTokenizer
from llmlb_tpu.structured import (
    ConstraintCompiler,
    ConstraintState,
    RegexSyntaxError,
    UnsupportedSchemaError,
    any_object_regex,
    compile_regex,
    inspect_request,
    parse_seed,
    schema_to_regex,
    spec_hash,
)

# ------------------------------------------------------------ regex engine


@pytest.mark.parametrize("pattern,ok,bad", [
    (r"-?(?:0|[1-9][0-9]*)", ["0", "-7", "123", "-100"], ["007", "-", "", "+1"]),
    (r"(?:true|false)", ["true", "false"], ["tru", "truex", "TRUE"]),
    (r"a{2,3}", ["aa", "aaa"], ["a", "aaaa"]),
    (r"[a-c]+", ["a", "abc", "ccc"], ["", "d", "abd"]),
    (r"[^x]*", ["", "ab", "yyy"], ["x", "ax"]),
    (r"a(?:b|c)*d", ["ad", "abd", "abccbd"], ["a", "abc"]),
    (r"\d{4}-\d{2}", ["2026-08"], ["2026-8", "20-08"]),
    (r'"(?:[^"\\]|\\.)*"', ['""', '"hi"', '"a\\"b"'], ['"', '"a', 'a"']),
], ids=["int", "bool", "braces", "class", "negclass", "group", "digits",
        "string"])
def test_regex_match(pattern, ok, bad):
    dfa = compile_regex(pattern)
    for text in ok:
        assert dfa.walk(dfa.start, text) in dfa.accepting, text
    for text in bad:
        end = dfa.walk(dfa.start, text)
        assert end is None or end not in dfa.accepting, text


def test_regex_rejects_unsupported_syntax():
    for pattern in ("(a", "a)", "[z-a]", "^abc$", r"\p{L}", "a{9999}",
                    "*a", "a{2,1}"):
        with pytest.raises(RegexSyntaxError):
            compile_regex(pattern)


def test_dead_states_pruned():
    # every surviving state can still reach acceptance
    dfa = compile_regex(r"ab|ac")
    for state in range(dfa.num_states):
        assert dfa.is_accepting(state) or dfa.trans[state]


# ----------------------------------------------------- schema -> regex


def _roundtrip(schema, text: str) -> bool:
    dfa = compile_regex(schema_to_regex(schema))
    end = dfa.walk(dfa.start, text)
    return end is not None and end in dfa.accepting


def test_schema_object_required_and_optional():
    schema = {
        "type": "object",
        "properties": {
            "a": {"type": "integer"},
            "b": {"type": "boolean"},
            "c": {"type": "string"},
        },
        "required": ["a"],
    }
    assert _roundtrip(schema, '{"a":1}')
    assert _roundtrip(schema, '{"a":1,"b":true}')
    assert _roundtrip(schema, '{"a":-2,"b":false,"c":"x"}')
    assert _roundtrip(schema, '{"a":1,"c":""}')
    assert not _roundtrip(schema, '{"b":true}')  # missing required
    assert not _roundtrip(schema, '{"a":1,"d":2}')  # closed object
    assert not _roundtrip(schema, '{"b":true,"a":1}')  # declaration order


def test_schema_matches_only_valid_instances():
    """Everything the grammar accepts must validate; a sample of invalid
    instances must be rejected — the guarantee the bench asserts end-to-end."""
    schema = {
        "type": "object",
        "properties": {
            "kind": {"enum": ["add", "del"]},
            "ids": {"type": "array", "items": {"type": "integer"},
                    "minItems": 1, "maxItems": 3},
            "note": {"type": ["string", "null"]},
        },
        "required": ["kind", "ids", "note"],
    }
    good = [
        {"kind": "add", "ids": [1], "note": None},
        {"kind": "del", "ids": [1, 2, 3], "note": "x"},
    ]
    for obj in good:
        text = json.dumps(obj, separators=(",", ":"))
        assert _roundtrip(schema, text), text
        jsonschema.validate(obj, schema)
    bad = [
        {"kind": "mul", "ids": [1], "note": None},
        {"kind": "add", "ids": [], "note": None},
        {"kind": "add", "ids": [1, 2, 3, 4], "note": None},
        {"kind": "add", "ids": [1], "note": 5},
    ]
    for obj in bad:
        text = json.dumps(obj, separators=(",", ":"))
        assert not _roundtrip(schema, text), text


def test_schema_refs_const_anyof():
    schema = {
        "$defs": {"id": {"type": "integer"}},
        "type": "object",
        "properties": {
            "v": {"const": 3},
            "x": {"anyOf": [{"$ref": "#/$defs/id"}, {"type": "null"}]},
        },
        "required": ["v", "x"],
    }
    assert _roundtrip(schema, '{"v":3,"x":9}')
    assert _roundtrip(schema, '{"v":3,"x":null}')
    assert not _roundtrip(schema, '{"v":4,"x":9}')


def test_schema_string_bounds_and_pattern():
    assert _roundtrip({"type": "string", "minLength": 2, "maxLength": 3},
                      '"ab"')
    assert not _roundtrip({"type": "string", "minLength": 2}, '"a"')
    assert _roundtrip({"type": "string", "pattern": "[a-z]{3}"}, '"abc"')
    assert not _roundtrip({"type": "string", "pattern": "[a-z]{3}"}, '"ab1"')


def test_json_object_mode_matches_any_object():
    dfa = compile_regex(any_object_regex())
    for text in ('{}', '{"a":1}', '{"a":{"b":[1,"x",null]},"c":true}'):
        assert dfa.walk(dfa.start, text) in dfa.accepting, text
    assert dfa.walk(dfa.start, '[1]') is None  # object, not array


@pytest.mark.parametrize("schema,feature", [
    ({"type": "object", "patternProperties": {"x": {}}}, "patternProperties"),
    ({"$dynamicRef": "#x"}, "$dynamicRef"),
    ({"allOf": [{"type": "string"}]}, "allOf"),
    ({"type": "number", "minimum": 3}, "minimum"),
    ({"type": "array", "uniqueItems": True}, "uniqueItems"),
    ({"$ref": "#/$defs/n", "$defs": {"n": {"$ref": "#/$defs/n"}}},
     "recursive $ref"),
    ({"type": "object",
      "properties": {c: {"type": "integer"} for c in "abcdefg"}},
     "optional properties"),
    ({"type": "string", "maxLength": 100000}, "maxLength"),
    # a pattern able to emit a raw quote would break the JSON guarantee
    ({"type": "string", "pattern": '[a-z"]+'}, "pattern"),
    ({"type": "string", "pattern": "[^a]+"}, "pattern"),
    # syntactically-broken patterns must fail at SCHEMA compile time (the
    # gateway's validation pass), never after a stream is committed
    ({"type": "string", "pattern": "(foo"}, "pattern"),
], ids=["patternProps", "dynamicRef", "allOf", "minimum", "uniqueItems",
        "recursiveRef", "tooManyOptional", "hugeMaxLength",
        "patternQuote", "patternNegClass", "patternBadSyntax"])
def test_unsupported_features_named_in_error(schema, feature):
    with pytest.raises(UnsupportedSchemaError) as exc:
        schema_to_regex(schema)
    assert feature in str(exc.value)


# ------------------------------------------------------- token constraints


@pytest.fixture(scope="module")
def compiler():
    return ConstraintCompiler(ByteTokenizer(512), 512)


def test_token_masks_follow_grammar(compiler):
    tc = compiler.compile_spec({"type": "regex", "pattern": r"-?[0-9]+"})
    state = ConstraintState(tc)
    row = tc.allowed[state.state]
    allowed = set(np.nonzero(row)[0].tolist())
    assert allowed == {ord("-")} | set(range(ord("0"), ord("9") + 1))
    assert state.advance(ord("-"))
    # after "-" a digit is mandatory; EOS is not allowed (not accepting)
    row = tc.allowed[state.state]
    assert not row[compiler.eos_id]
    assert state.advance(ord("4"))
    assert state.is_accepting
    assert tc.allowed[state.state][compiler.eos_id]
    assert state.advance(compiler.eos_id)
    assert not state.violated


def test_constraint_violation_flag(compiler):
    tc = compiler.compile_spec({"type": "regex", "pattern": "ab"})
    state = ConstraintState(tc)
    assert not state.advance(ord("x"))
    assert state.violated
    # EOS before acceptance is a violation too
    state2 = ConstraintState(tc)
    assert not state2.advance(compiler.eos_id)
    assert state2.violated


def test_greedy_mask_walk_terminates_with_valid_json(compiler):
    schema = {"type": "object",
              "properties": {"ok": {"type": "boolean"},
                             "tag": {"enum": ["x", "y"]}},
              "required": ["ok", "tag"]}
    tc = compiler.compile_spec({"type": "json_schema", "schema": schema})
    tok = ByteTokenizer(512)
    state = ConstraintState(tc)
    out = []
    for _ in range(200):
        ids = np.nonzero(tc.allowed[state.state])[0]
        assert len(ids)
        chosen = int(ids[0]) if int(ids[0]) != tok.eos_id else int(ids[-1])
        if chosen == tok.eos_id:
            assert state.is_accepting
            break
        assert state.advance(chosen)
        out.append(chosen)
    else:
        pytest.fail("grammar never terminated")
    jsonschema.validate(json.loads(tok.decode(out)), schema)


def test_empty_decoding_tokens_never_allowed(compiler):
    """Ids decoding to nothing (pad/bos and ids >= 258 on the byte
    tokenizer) must be masked everywhere — they would stall the grammar."""
    tc = compiler.compile_spec({"type": "json_object"})
    dead = [257, 300, 511]
    for state in range(tc.num_states):
        assert not tc.allowed[state, dead].any()


def test_lru_cache_hits_and_evictions():
    comp = ConstraintCompiler(ByteTokenizer(512), 512, max_entries=2)
    a = comp.compile_spec({"type": "regex", "pattern": "a"})
    assert comp.compile_spec({"type": "regex", "pattern": "a"}) is a
    assert comp.compile_cache_hits == 1 and comp.compile_cache_misses == 1
    comp.compile_spec({"type": "regex", "pattern": "b"})
    comp.compile_spec({"type": "regex", "pattern": "c"})  # evicts "a"
    assert comp.evictions == 1
    a2 = comp.compile_spec({"type": "regex", "pattern": "a"})  # recompiled
    assert a2 is not a
    info = comp.info()
    assert info["mask_cache_entries"] == 2
    assert info["mask_cache_bytes"] > 0
    assert info["compile_cache_hit_rate"] is not None


def test_spec_hash_is_stable_and_order_independent():
    s1 = {"type": "json_schema", "schema": {"a": 1, "b": 2}}
    s2 = {"schema": {"b": 2, "a": 1}, "type": "json_schema"}
    assert spec_hash(s1) == spec_hash(s2)
    assert spec_hash(s1) != spec_hash({"type": "json_object"})


# -------------------------------------------------- OpenAI request parsing


def test_inspect_request_kinds():
    assert inspect_request({"messages": []}) is None
    assert inspect_request({"response_format": {"type": "text"}}) is None
    r = inspect_request({"response_format": {"type": "json_object"}})
    assert r.kind == "json_object"
    schema = {"type": "object", "properties": {}, "required": []}
    r = inspect_request({"response_format": {
        "type": "json_schema", "json_schema": {"name": "t", "schema": schema}
    }})
    assert r.kind == "json_schema" and r.spec["schema"] == schema
    tools = [{"type": "function",
              "function": {"name": "f", "parameters": schema}}]
    r = inspect_request({
        "tools": tools,
        "tool_choice": {"type": "function", "function": {"name": "f"}},
    })
    assert r.kind == "tool_call" and r.tool_name == "f"
    r = inspect_request({"tools": tools, "tool_choice": "required"})
    assert r.kind == "tool_call"
    # auto/none and required-with-many-tools pass through unconstrained
    assert inspect_request({"tools": tools, "tool_choice": "auto"}) is None
    assert inspect_request(
        {"tools": tools * 2, "tool_choice": "required"}
    ) is None


def test_inspect_request_rejections():
    with pytest.raises(ValueError):
        inspect_request({"response_format": {"type": "bogus"}})
    with pytest.raises(ValueError):
        inspect_request({"response_format": {"type": "json_schema"}})
    with pytest.raises(ValueError):
        inspect_request({"tool_choice": {"type": "function",
                                         "function": {"name": "missing"}}})
    with pytest.raises(UnsupportedSchemaError):
        inspect_request({"response_format": {
            "type": "json_schema",
            "json_schema": {"name": "x",
                            "schema": {"type": "object",
                                       "patternProperties": {}}},
        }})
    with pytest.raises(ValueError):
        inspect_request({
            "response_format": {"type": "json_object"},
            "tools": [{"type": "function", "function": {"name": "f"}}],
            "tool_choice": {"type": "function", "function": {"name": "f"}},
        })


def test_parse_seed():
    assert parse_seed({}) is None
    assert parse_seed({"seed": 42}) == 42
    assert parse_seed({"seed": -1}) >= 0  # folded into uint31 space
    with pytest.raises(ValueError):
        parse_seed({"seed": "42"})
    with pytest.raises(ValueError):
        parse_seed({"seed": True})
