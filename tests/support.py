"""Test support: in-process gateway + mock upstream endpoints.

Port of the reference's test harness pattern (tests/support/lb.rs:16-110 test
AppState builder, support/ollama.rs + node.rs mock endpoints, support/http.rs
ephemeral-port spawner): register N mock endpoint URLs and exercise selection /
health / failover / streaming entirely in-process, no TPUs required.
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.gateway.app import create_app
from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.registry import EndpointRegistry  # noqa: F401
from llmlb_tpu.gateway.types import (
    Capability,
    Endpoint,
    EndpointModel,
    EndpointStatus,
    EndpointType,
)

TEST_JWT_SECRET = "test-jwt-secret"
ADMIN_PASSWORD = "adminpass1"


class MockOpenAIEndpoint:
    """A fake OpenAI-compatible runtime with configurable behavior."""

    def __init__(self, *, model="mock-model", tokens_per_reply=5,
                 reply_delay_s=0.0, inter_chunk_delay_s=0.0,
                 fail_with: int | None = None,
                 include_usage=True):
        self.model = model
        self.tokens_per_reply = tokens_per_reply
        self.reply_delay_s = reply_delay_s
        # stream mode: sleep between SSE chunks so the proxy sees them as
        # separate reads (a local TestServer otherwise delivers the whole
        # body in one iter_any chunk)
        self.inter_chunk_delay_s = inter_chunk_delay_s
        self.fail_with = fail_with
        self.include_usage = include_usage
        self.requests_seen: list[dict] = []
        self.headers_seen: list[dict] = []  # per-request inbound headers
        self.server: TestServer | None = None

    @property
    def url(self) -> str:
        assert self.server is not None
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self) -> "MockOpenAIEndpoint":
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._chat)
        app.router.add_post("/v1/responses", self._chat)
        app.router.add_post("/v1/embeddings", self._embeddings)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def stop(self) -> None:
        if self.server:
            await self.server.close()

    async def _models(self, request):
        return web.json_response(
            {"object": "list", "data": [{"id": self.model, "object": "model"}]}
        )

    async def _chat(self, request):
        body = await request.json()
        self.requests_seen.append(body)
        self.headers_seen.append(dict(request.headers))
        if self.fail_with:
            return web.json_response({"error": "induced"}, status=self.fail_with)
        if self.reply_delay_s:
            await asyncio.sleep(self.reply_delay_s)
        n = self.tokens_per_reply
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for i in range(n):
                chunk = {
                    "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                    "model": body.get("model"),
                    "choices": [{"index": 0, "delta": {"content": f"tok{i} "},
                                 "finish_reason": None}],
                }
                await resp.write(
                    b"data: " + json.dumps(chunk).encode() + b"\n\n"
                )
                if self.inter_chunk_delay_s:
                    await asyncio.sleep(self.inter_chunk_delay_s)
            final = {
                "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                "model": body.get("model"),
                "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
            }
            await resp.write(b"data: " + json.dumps(final).encode() + b"\n\n")
            if self.include_usage:
                usage_chunk = {
                    "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                    "choices": [],
                    "usage": {"prompt_tokens": 7, "completion_tokens": n,
                              "total_tokens": 7 + n},
                }
                await resp.write(
                    b"data: " + json.dumps(usage_chunk).encode() + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            return resp
        payload = {
            "id": "chatcmpl-mock", "object": "chat.completion",
            "model": body.get("model"),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant",
                            "content": " ".join(f"tok{i}" for i in range(n))},
                "finish_reason": "stop",
            }],
        }
        if self.include_usage:
            payload["usage"] = {
                "prompt_tokens": 7, "completion_tokens": n, "total_tokens": 7 + n,
            }
        return web.json_response(payload)

    async def _embeddings(self, request):
        body = await request.json()
        self.requests_seen.append(body)
        return web.json_response({
            "object": "list",
            "data": [{"object": "embedding", "index": 0,
                      "embedding": [0.1, 0.2, 0.3]}],
            "model": body.get("model"),
            "usage": {"prompt_tokens": 4, "total_tokens": 4},
        })


class MockOllamaEndpoint:
    """Speaks Ollama's discovery surface (/api/tags) for detection/sync tests."""

    def __init__(self, models=("llama3:8b",)):
        self.models = list(models)
        self.server: TestServer | None = None

    @property
    def url(self) -> str:
        assert self.server is not None
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self) -> "MockOllamaEndpoint":
        app = web.Application()
        app.router.add_get("/api/tags", self._tags)
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/api/show", self._show)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _show(self, request):
        body = await request.json()
        if body.get("name") not in self.models:
            return web.json_response({"error": "model not found"}, status=404)
        return web.json_response({
            "details": {"family": "llama"},
            "model_info": {"llama.context_length": 8192},
        })

    async def stop(self) -> None:
        if self.server:
            await self.server.close()

    async def _tags(self, request):
        return web.json_response(
            {"models": [{"name": m} for m in self.models]}
        )

    async def _models(self, request):
        return web.json_response(
            {"object": "list", "data": [{"id": m} for m in self.models]}
        )


class GatewayHarness:
    """In-process gateway with real middlewares over an in-memory DB."""

    def __init__(self, state, client: TestClient):
        self.state = state
        self.client = client
        self._admin_token: str | None = None
        self._api_key: str | None = None

    @classmethod
    async def create(cls, *, start_background=False) -> "GatewayHarness":
        import os

        os.environ["LLMLB_ADMIN_PASSWORD"] = ADMIN_PASSWORD
        os.environ["LLMLB_JWT_SECRET"] = TEST_JWT_SECRET
        config = ServerConfig.from_env()
        state = await build_app_state(
            config, db=Database(":memory:"), start_background=start_background
        )
        app = create_app(state)
        client = TestClient(TestServer(app))
        await client.start_server()
        return cls(state, client)

    async def close(self) -> None:
        await self.client.close()

    # ------------------------------------------------------------ auth helpers

    async def admin_token(self) -> str:
        if self._admin_token is None:
            resp = await self.client.post("/api/auth/login", json={
                "username": "admin", "password": ADMIN_PASSWORD,
            })
            assert resp.status == 200, await resp.text()
            self._admin_token = (await resp.json())["token"]
        return self._admin_token

    async def admin_headers(self) -> dict:
        return {"Authorization": f"Bearer {await self.admin_token()}"}

    async def inference_key(self) -> str:
        if self._api_key is None:
            resp = await self.client.post(
                "/api/api-keys",
                json={"name": "test", "permissions": [
                    "openai.inference", "openai.models.read"]},
                headers=await self.admin_headers(),
            )
            assert resp.status == 201, await resp.text()
            self._api_key = (await resp.json())["api_key"]
        return self._api_key

    async def inference_headers(self) -> dict:
        return {"Authorization": f"Bearer {await self.inference_key()}"}

    # -------------------------------------------------------------- endpoints

    def register_mock(
        self, url: str, models: list[str],
        endpoint_type=EndpointType.OPENAI_COMPATIBLE,
        capabilities=None, name=None,
    ) -> Endpoint:
        """Register an endpoint directly in the registry, already ONLINE."""
        ep = Endpoint(
            name=name or url, base_url=url, endpoint_type=endpoint_type,
            status=EndpointStatus.ONLINE,
        )
        self.state.registry.add(ep)
        self.state.registry.sync_models(ep.id, [
            EndpointModel(
                endpoint_id=ep.id, model_id=m, canonical_name=m,
                capabilities=capabilities or [Capability.CHAT_COMPLETION],
            )
            for m in models
        ])
        return ep
