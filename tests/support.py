"""Test support: in-process gateway + mock upstream endpoints.

Port of the reference's test harness pattern (tests/support/lb.rs:16-110 test
AppState builder, support/ollama.rs + node.rs mock endpoints, support/http.rs
ephemeral-port spawner): register N mock endpoint URLs and exercise selection /
health / failover / streaming entirely in-process, no TPUs required.
"""

from __future__ import annotations

import asyncio
import json

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from llmlb_tpu.gateway.app import create_app
from llmlb_tpu.gateway.app_state import build_app_state
from llmlb_tpu.gateway.config import ServerConfig
from llmlb_tpu.gateway.db import Database
from llmlb_tpu.gateway.registry import EndpointRegistry  # noqa: F401
from llmlb_tpu.gateway.types import (
    Capability,
    Endpoint,
    EndpointModel,
    EndpointStatus,
    EndpointType,
)

TEST_JWT_SECRET = "test-jwt-secret"
ADMIN_PASSWORD = "adminpass1"


# --------------------------------------------------- SSE protocol invariants


def parse_sse_frames(body: bytes) -> list[dict]:
    """Split a raw SSE body into frames: [{"event": str|None, "data": [raw
    data strings]}]. Frames are terminated by a blank line; a trailing
    partial frame (no terminator — a cut stream) is included as-is."""
    frames: list[dict] = []
    for block in body.split(b"\n\n"):
        if not block.strip():
            continue
        frame = {"event": None, "data": []}
        for line in block.split(b"\n"):
            line = line.strip()
            if line.startswith(b"event:"):
                frame["event"] = line[len(b"event:"):].strip().decode()
            elif line.startswith(b"data:"):
                frame["data"].append(line[len(b"data:"):].strip().decode())
        if frame["event"] is not None or frame["data"]:
            frames.append(frame)
    return frames


def assert_sse_protocol(body: bytes, dialect: str = "openai",
                        allow_error: bool = False) -> None:
    """Protocol-invariant checker for gateway SSE streams (applied to every
    gateway stream test, not just the resume tests):

    - exactly one role delta (OpenAI) / exactly one message_start
      (Anthropic) — a spliced resume must never re-open the message;
    - monotone indices (OpenAI choice index non-decreasing; Anthropic
      content_block indices strictly increasing, deltas only to the open
      block);
    - exactly one terminal frame (``[DONE]`` / ``message_stop``) and no
      frames after it; with ``allow_error`` an ``event: error`` frame may
      terminate instead (optionally followed by one ``[DONE]``);
    - no gateway-internal ``llmlb.replay`` frames leak to the client.
    """
    frames = parse_sse_frames(body)
    assert frames, "stream produced no SSE frames"
    if dialect == "openai":
        _assert_openai_stream(frames, allow_error)
    elif dialect == "anthropic":
        _assert_anthropic_stream(frames, allow_error)
    else:  # pragma: no cover - test-author error
        raise ValueError(f"unknown dialect {dialect!r}")


def _assert_openai_stream(frames: list[dict], allow_error: bool) -> None:
    done_seen = 0
    error_seen = 0
    role_deltas = 0
    last_choice_index = -1
    terminal_at: int | None = None
    for i, frame in enumerate(frames):
        if terminal_at is not None and frame["data"] != []:
            raise AssertionError(
                f"frame after terminal [DONE]: {frame!r}"
            )
        if frame["event"] == "error":
            error_seen += 1
            assert allow_error, f"unexpected error frame: {frame!r}"
            continue
        for raw in frame["data"]:
            if raw == "[DONE]":
                done_seen += 1
                terminal_at = i
                continue
            try:
                obj = json.loads(raw)
            except ValueError:
                if allow_error:
                    # an interrupted byte-passthrough stream may end with a
                    # truncated partial frame before the error frame — the
                    # one shape a cut legitimately produces
                    continue
                raise AssertionError(f"unparseable data frame: {raw!r}")
            if not isinstance(obj, dict):
                continue
            assert obj.get("object") != "llmlb.replay", (
                "gateway-internal llmlb.replay frame leaked to the client"
            )
            if "error" in obj and "choices" not in obj:
                error_seen += 1
                assert allow_error, f"unexpected error payload: {raw!r}"
                continue
            for choice in obj.get("choices") or []:
                idx = choice.get("index", 0)
                assert idx >= last_choice_index, (
                    f"choice index went backwards: {idx} after "
                    f"{last_choice_index}"
                )
                last_choice_index = max(last_choice_index, idx)
                delta = choice.get("delta") or {}
                if delta.get("role"):
                    role_deltas += 1
    assert done_seen <= 1, f"{done_seen} [DONE] frames (expected exactly 1)"
    if error_seen == 0:
        assert done_seen == 1, "completed stream must end with one [DONE]"
    assert role_deltas <= 1, (
        f"{role_deltas} role deltas (a resumed stream must not re-open "
        "the message)"
    )


def _assert_anthropic_stream(frames: list[dict], allow_error: bool) -> None:
    starts = 0
    stops = 0
    open_block: int | None = None
    last_block_index = -1
    terminal = False
    for frame in frames:
        assert not terminal, f"frame after message_stop: {frame!r}"
        if frame["event"] == "error":
            assert allow_error, f"unexpected error event: {frame!r}"
            terminal = True
            continue
        for raw in frame["data"]:
            try:
                obj = json.loads(raw)
            except ValueError:
                if allow_error:
                    continue  # truncated partial frame on a cut stream
                raise AssertionError(f"unparseable data frame: {raw!r}")
            etype = obj.get("type")
            if etype == "message_start":
                starts += 1
                assert starts == 1, "second message_start on one stream"
            elif etype == "content_block_start":
                idx = obj.get("index")
                assert open_block is None, (
                    f"content_block_start for {idx} while block "
                    f"{open_block} is open"
                )
                assert idx > last_block_index, (
                    f"content_block index not increasing: {idx} after "
                    f"{last_block_index}"
                )
                open_block = idx
                last_block_index = idx
            elif etype == "content_block_delta":
                assert obj.get("index") == open_block, (
                    f"delta for block {obj.get('index')} but open block "
                    f"is {open_block}"
                )
            elif etype == "content_block_stop":
                assert obj.get("index") == open_block, (
                    f"stop for block {obj.get('index')} but open block "
                    f"is {open_block}"
                )
                open_block = None
            elif etype == "message_stop":
                stops += 1
                terminal = True
            elif etype == "error":
                assert allow_error, f"unexpected error payload: {raw!r}"
                terminal = True
    assert starts == 1 or (allow_error and starts == 0), (
        "stream must carry exactly one message_start"
    )
    if not allow_error:
        assert stops == 1, "stream must end with exactly one message_stop"
    assert stops <= 1, f"{stops} message_stop events"


class MockOpenAIEndpoint:
    """A fake OpenAI-compatible runtime with configurable behavior."""

    def __init__(self, *, model="mock-model", tokens_per_reply=5,
                 reply_delay_s=0.0, inter_chunk_delay_s=0.0,
                 fail_with: int | None = None,
                 include_usage=True):
        self.model = model
        self.tokens_per_reply = tokens_per_reply
        self.reply_delay_s = reply_delay_s
        # stream mode: sleep between SSE chunks so the proxy sees them as
        # separate reads (a local TestServer otherwise delivers the whole
        # body in one iter_any chunk)
        self.inter_chunk_delay_s = inter_chunk_delay_s
        self.fail_with = fail_with
        self.include_usage = include_usage
        self.requests_seen: list[dict] = []
        self.headers_seen: list[dict] = []  # per-request inbound headers
        self.server: TestServer | None = None

    @property
    def url(self) -> str:
        assert self.server is not None
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self) -> "MockOpenAIEndpoint":
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._chat)
        app.router.add_post("/v1/responses", self._chat)
        app.router.add_post("/v1/embeddings", self._embeddings)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def stop(self) -> None:
        if self.server:
            await self.server.close()

    async def _models(self, request):
        return web.json_response(
            {"object": "list", "data": [{"id": self.model, "object": "model"}]}
        )

    async def _chat(self, request):
        body = await request.json()
        self.requests_seen.append(body)
        self.headers_seen.append(dict(request.headers))
        if self.fail_with:
            return web.json_response({"error": "induced"}, status=self.fail_with)
        if self.reply_delay_s:
            await asyncio.sleep(self.reply_delay_s)
        n = self.tokens_per_reply
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            for i in range(n):
                chunk = {
                    "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                    "model": body.get("model"),
                    "choices": [{"index": 0, "delta": {"content": f"tok{i} "},
                                 "finish_reason": None}],
                }
                await resp.write(
                    b"data: " + json.dumps(chunk).encode() + b"\n\n"
                )
                if self.inter_chunk_delay_s:
                    await asyncio.sleep(self.inter_chunk_delay_s)
            final = {
                "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                "model": body.get("model"),
                "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
            }
            await resp.write(b"data: " + json.dumps(final).encode() + b"\n\n")
            if self.include_usage:
                usage_chunk = {
                    "id": "chatcmpl-mock", "object": "chat.completion.chunk",
                    "choices": [],
                    "usage": {"prompt_tokens": 7, "completion_tokens": n,
                              "total_tokens": 7 + n},
                }
                await resp.write(
                    b"data: " + json.dumps(usage_chunk).encode() + b"\n\n"
                )
            await resp.write(b"data: [DONE]\n\n")
            return resp
        payload = {
            "id": "chatcmpl-mock", "object": "chat.completion",
            "model": body.get("model"),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant",
                            "content": " ".join(f"tok{i}" for i in range(n))},
                "finish_reason": "stop",
            }],
        }
        if self.include_usage:
            payload["usage"] = {
                "prompt_tokens": 7, "completion_tokens": n, "total_tokens": 7 + n,
            }
        return web.json_response(payload)

    async def _embeddings(self, request):
        body = await request.json()
        self.requests_seen.append(body)
        return web.json_response({
            "object": "list",
            "data": [{"object": "embedding", "index": 0,
                      "embedding": [0.1, 0.2, 0.3]}],
            "model": body.get("model"),
            "usage": {"prompt_tokens": 4, "total_tokens": 4},
        })


class MockResumableEndpoint(MockOpenAIEndpoint):
    """A mock tpu:// engine for durable-stream tests: streams a scripted
    token sequence with gateway-internal ``llmlb.replay`` frames when the
    request is armed (``llmlb_replay: true``), and adopts cut streams on
    ``/v1/resume`` — replaying the committed ids and emitting the FULL text
    exactly as a real engine's adopt path does (token i renders as
    ``t<i> ``, deterministic across instances, so splice identity is
    checkable byte for byte)."""

    def __init__(self, *, model="mock-model", script=None,
                 tokens_per_chunk=1, inter_chunk_delay_s=0.002,
                 resume_fail_with: int | None = None):
        super().__init__(model=model,
                         inter_chunk_delay_s=inter_chunk_delay_s)
        # the full token sequence every instance of this "model" generates
        self.script = list(script if script is not None else range(100, 112))
        self.tokens_per_chunk = max(1, tokens_per_chunk)
        self.resume_fail_with = resume_fail_with
        self.resume_calls: list[dict] = []
        # /v1/kv/export behavior (proactive migration tests): None = serve
        # an opaque kv_pages payload; an int = refuse with that status
        # (an origin that cannot park right now, or an old build 404ing)
        self.export_fail_with: int | None = None
        self.export_calls: list[dict] = []
        # graceful-drain advertisement (flip from tests; the gateway's
        # health probe re-parses it every cycle)
        self.draining = False
        self.drain_remaining_s = 0.0

    @staticmethod
    def text_of(token_id: int) -> str:
        return f"t{token_id} "

    async def start(self) -> "MockResumableEndpoint":
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/api/health", self._health)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/resume", self._resume)
        app.router.add_post("/v1/kv/export", self._kv_export)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _health(self, request):
        return web.json_response({
            "status": "draining" if self.draining else "ok",
            "tpu": {"accelerator": "tpu", "chip_count": 1},
            "engine": {"num_slots": 4, "active_slots": 0, "queued": 0},
            "draining": {"draining": self.draining, "grace_s": 30.0,
                         "remaining_s": self.drain_remaining_s},
        })

    async def _stream_script(self, request, body, start_token: int):
        """Stream self.script[start:] as chat chunks; with llmlb_replay,
        each chunk's ids ship first as an llmlb.replay frame (the engine
        contract: tokens always cover every character already sent)."""
        armed = bool(body.get("llmlb_replay"))
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream"}
        )
        await resp.prepare(request)

        async def send(obj) -> None:
            await resp.write(
                b"data: " + json.dumps(obj).encode() + b"\n\n"
            )

        def chunk(delta, finish=None):
            return {
                "id": "chatcmpl-mockresume", "object": "chat.completion.chunk",
                "created": 1700000000, "model": body.get("model"),
                "choices": [{"index": 0, "delta": delta,
                             "finish_reason": finish}],
            }

        await send(chunk({"role": "assistant", "content": ""}))
        toks = self.script[start_token:]
        for i in range(0, len(toks), self.tokens_per_chunk):
            group = toks[i:i + self.tokens_per_chunk]
            if armed:
                await send({"object": "llmlb.replay", "tokens": group})
            await send(chunk(
                {"content": "".join(self.text_of(t) for t in group)}
            ))
            if self.inter_chunk_delay_s:
                await asyncio.sleep(self.inter_chunk_delay_s)
        await send(chunk({}, "stop"))
        await send({
            "id": "chatcmpl-mockresume", "object": "chat.completion.chunk",
            "choices": [],
            "usage": {"prompt_tokens": 7,
                      "completion_tokens": len(self.script),
                      "total_tokens": 7 + len(self.script)},
        })
        await resp.write(b"data: [DONE]\n\n")
        return resp

    async def _chat(self, request):
        body = await request.json()
        self.requests_seen.append(body)
        self.headers_seen.append(dict(request.headers))
        if self.fail_with:
            return web.json_response({"error": "induced"},
                                     status=self.fail_with)
        if not body.get("stream"):
            return await super()._chat(request)
        return await self._stream_script(request, body, 0)

    async def _kv_export(self, request):
        body = await request.json()
        self.export_calls.append(body)
        if self.export_fail_with:
            return web.json_response({"error": "induced"},
                                     status=self.export_fail_with)
        # opaque payload: the gateway forwards it verbatim to /v1/resume
        # (a real engine would refuse a mismatched payload and replay)
        return web.json_response({
            "request_id": body.get("request_id"),
            "kv_pages": {"mock": True, "park": bool(body.get("park"))},
        })

    async def _resume(self, request):
        body = await request.json()
        self.resume_calls.append(body)
        if self.resume_fail_with:
            return web.json_response({"error": "induced"},
                                     status=self.resume_fail_with)
        committed = body.get("committed_ids") or []
        # a real engine replays prompt+committed then CONTINUES — committed
        # ids must be a prefix of what this model deterministically generates
        assert committed == self.script[:len(committed)], (
            f"committed ids {committed} are not a prefix of {self.script}"
        )
        # full text from token 0: the adopt path re-emits committed text and
        # the gateway splices off what its client already holds
        return await self._stream_script(request, body, 0)


class MockDisaggEndpoint(MockOpenAIEndpoint):
    """A mock tpu:// engine with a disaggregation role: advertises the role
    on /v1/models capabilities and /api/health, answers /v1/handoff/prefill
    with a real wire payload (prefill role), and adopts payloads on
    /v1/handoff (decode role). The adopt reply's content embeds what
    arrived on the wire so tests can assert fields survived."""

    def __init__(self, *, role="both", model="mock-model",
                 tokens_per_reply=5, handoff_fail_with=None):
        super().__init__(model=model, tokens_per_reply=tokens_per_reply)
        self.role = role
        self.handoff_fail_with = handoff_fail_with
        self.prefill_calls: list[dict] = []  # /v1/handoff/prefill bodies
        self.adopt_calls: list[dict] = []  # /v1/handoff bodies
        self.adopt_headers: list[dict] = []

    async def start(self) -> "MockDisaggEndpoint":
        app = web.Application()
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_get("/api/health", self._health)
        app.router.add_post("/v1/handoff/prefill", self._prefill)
        app.router.add_post("/v1/handoff", self._adopt)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _models(self, request):
        caps = ["chat_completion"]
        if self.role in ("both", "split", "prefill"):
            caps.append("prefill")
        if self.role in ("both", "split", "decode"):
            caps.append("decode")
        return web.json_response({
            "object": "list",
            "data": [{"id": self.model, "object": "model",
                      "capabilities": caps, "role": self.role}],
        })

    async def _health(self, request):
        return web.json_response({
            "status": "ok",
            "tpu": {"accelerator": "tpu", "chip_count": 1},
            "engine": {"num_slots": 4, "active_slots": 0, "queued": 0},
            "disagg": {"role": self.role, "split": self.role == "split",
                       "handoff_total": {}, "handoff_backlog": 0},
        })

    async def _prefill(self, request):
        from llmlb_tpu.disagg import handoff_payload
        from llmlb_tpu.engine.scheduler import SamplingParams

        body = await request.json()
        self.prefill_calls.append(
            {"body": body, "headers": dict(request.headers)}
        )
        if self.handoff_fail_with:
            return web.json_response({"error": "induced"},
                                     status=self.handoff_fail_with)
        deadline = request.headers.get("X-Request-Deadline-Ms")
        sampling = SamplingParams(
            temperature=float(body.get("temperature") or 1.0),
            max_tokens=int(body.get("max_tokens") or 16),
            priority={"high": 0, "normal": 1, "low": 2}.get(
                body.get("priority"), body.get("priority") or 1
            ) if body.get("priority") is not None else 1,
            deadline_ms=float(deadline) if deadline else None,
        )
        payload = handoff_payload(
            [1, 2, 3], [7], sampling,
            request_id=request.headers.get("X-Request-Id"),
        )
        return web.json_response({
            "object": "llmlb.handoff", "model": self.model,
            "handoff": payload, "finish": None, "tool_name": None,
            "usage": {"prompt_tokens": 3, "completion_tokens": 1,
                      "total_tokens": 4},
        })

    async def _adopt(self, request):
        body = await request.json()
        self.adopt_calls.append(body)
        self.adopt_headers.append(dict(request.headers))
        handoff = body.get("handoff") or {}
        sampling = handoff.get("sampling") or {}
        content = json.dumps({
            "adopted_by": self.role,
            "committed": handoff.get("committed_ids"),
            "priority": sampling.get("priority"),
            "deadline_ms": sampling.get("deadline_ms"),
        })
        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream"}
            )
            await resp.prepare(request)
            chunk = {
                "id": "chatcmpl-adopt", "object": "chat.completion.chunk",
                "model": body.get("model"),
                "choices": [{"index": 0, "delta": {"content": content},
                             "finish_reason": None}],
            }
            await resp.write(b"data: " + json.dumps(chunk).encode() + b"\n\n")
            final = {
                "id": "chatcmpl-adopt", "object": "chat.completion.chunk",
                "model": body.get("model"),
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": "stop"}],
                "usage": {"prompt_tokens": 3, "completion_tokens": 5,
                          "total_tokens": 8},
            }
            await resp.write(b"data: " + json.dumps(final).encode() + b"\n\n")
            await resp.write(b"data: [DONE]\n\n")
            return resp
        return web.json_response({
            "id": "chatcmpl-adopt", "object": "chat.completion",
            "model": body.get("model"),
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": content},
                "finish_reason": "stop",
            }],
            "usage": {"prompt_tokens": 3, "completion_tokens": 5,
                      "total_tokens": 8},
        })


class MockOllamaEndpoint:
    """Speaks Ollama's discovery surface (/api/tags) for detection/sync tests."""

    def __init__(self, models=("llama3:8b",)):
        self.models = list(models)
        self.server: TestServer | None = None

    @property
    def url(self) -> str:
        assert self.server is not None
        return f"http://127.0.0.1:{self.server.port}"

    async def start(self) -> "MockOllamaEndpoint":
        app = web.Application()
        app.router.add_get("/api/tags", self._tags)
        app.router.add_get("/v1/models", self._models)
        app.router.add_post("/api/show", self._show)
        self.server = TestServer(app)
        await self.server.start_server()
        return self

    async def _show(self, request):
        body = await request.json()
        if body.get("name") not in self.models:
            return web.json_response({"error": "model not found"}, status=404)
        return web.json_response({
            "details": {"family": "llama"},
            "model_info": {"llama.context_length": 8192},
        })

    async def stop(self) -> None:
        if self.server:
            await self.server.close()

    async def _tags(self, request):
        return web.json_response(
            {"models": [{"name": m} for m in self.models]}
        )

    async def _models(self, request):
        return web.json_response(
            {"object": "list", "data": [{"id": m} for m in self.models]}
        )


class GatewayHarness:
    """In-process gateway with real middlewares over an in-memory DB."""

    def __init__(self, state, client: TestClient):
        self.state = state
        self.client = client
        self._admin_token: str | None = None
        self._api_key: str | None = None

    @classmethod
    async def create(cls, *, start_background=False) -> "GatewayHarness":
        import os

        os.environ["LLMLB_ADMIN_PASSWORD"] = ADMIN_PASSWORD
        os.environ["LLMLB_JWT_SECRET"] = TEST_JWT_SECRET
        config = ServerConfig.from_env()
        state = await build_app_state(
            config, db=Database(":memory:"), start_background=start_background
        )
        app = create_app(state)
        client = TestClient(TestServer(app))
        await client.start_server()
        return cls(state, client)

    async def close(self) -> None:
        await self.client.close()

    # ------------------------------------------------------------ auth helpers

    async def admin_token(self) -> str:
        if self._admin_token is None:
            resp = await self.client.post("/api/auth/login", json={
                "username": "admin", "password": ADMIN_PASSWORD,
            })
            assert resp.status == 200, await resp.text()
            self._admin_token = (await resp.json())["token"]
        return self._admin_token

    async def admin_headers(self) -> dict:
        return {"Authorization": f"Bearer {await self.admin_token()}"}

    async def inference_key(self) -> str:
        if self._api_key is None:
            resp = await self.client.post(
                "/api/api-keys",
                json={"name": "test", "permissions": [
                    "openai.inference", "openai.models.read"]},
                headers=await self.admin_headers(),
            )
            assert resp.status == 201, await resp.text()
            self._api_key = (await resp.json())["api_key"]
        return self._api_key

    async def inference_headers(self) -> dict:
        return {"Authorization": f"Bearer {await self.inference_key()}"}

    # -------------------------------------------------------------- endpoints

    def register_mock(
        self, url: str, models: list[str],
        endpoint_type=EndpointType.OPENAI_COMPATIBLE,
        capabilities=None, name=None,
    ) -> Endpoint:
        """Register an endpoint directly in the registry, already ONLINE."""
        ep = Endpoint(
            name=name or url, base_url=url, endpoint_type=endpoint_type,
            status=EndpointStatus.ONLINE,
        )
        self.state.registry.add(ep)
        self.state.registry.sync_models(ep.id, [
            EndpointModel(
                endpoint_id=ep.id, model_id=m, canonical_name=m,
                capabilities=capabilities or [Capability.CHAT_COMPLETION],
            )
            for m in models
        ])
        return ep
