"""Tier-1 guard: every LLMLB_* env knob is documented.

Runs scripts/check_env_docs.py's cross-check in-process: any
`LLMLB_[A-Z0-9_]+` name referenced in llmlb_tpu/ must appear verbatim
somewhere under docs/ (docs/configuration.md is the canonical table), so
a new knob — like LLMLB_QUANTIZE — cannot ship undocumented.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_env_docs  # noqa: E402


def test_all_env_knobs_are_documented():
    missing = check_env_docs.undocumented()
    assert not missing, f"undocumented LLMLB_* env knobs: {missing}"


def test_enumeration_is_not_vacuous():
    """The source scan must find the well-known knobs (no silent pass if
    the glob or regex breaks)."""
    knobs = check_env_docs.source_knobs()
    for expected in ("LLMLB_QUANTIZE", "LLMLB_KV_LAYOUT",
                     "LLMLB_DECODE_BURST", "LLMLB_PREFIX_CACHE"):
        assert expected in knobs, expected
    # glob-style prose ("LLMLB_SPEC_{DECODE,...}") must not leak partials
    assert "LLMLB_SPEC" not in knobs or "LLMLB_SPEC_DECODE" in knobs


def test_checker_catches_missing_knob(monkeypatch):
    """The checker itself must fail on an undocumented knob."""
    real = check_env_docs.source_knobs

    def with_fake():
        return real() | {"LLMLB_NOT_A_REAL_KNOB"}

    monkeypatch.setattr(check_env_docs, "source_knobs", with_fake)
    assert "LLMLB_NOT_A_REAL_KNOB" in check_env_docs.undocumented()
