"""Tier-1 guard: fused decode launches exactly ONE device program per step.

Runs scripts/check_fused_dispatch.py's runtime check in-process: a CPU
debug engine with quantized KV + LoRA + speculation + a JSON-schema
constraint all active must record dispatches == 1 on every decode/verify
step of its ledger under LLMLB_FUSED_DECODE=1, with zero constrained
single-step fallbacks — the invariant the fused dispatch PR exists to
hold (docs/fused-decode.md).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_fused_dispatch  # noqa: E402


def test_fused_decode_is_one_dispatch_per_step():
    findings = check_fused_dispatch.run_check()
    assert not findings, "\n".join(findings)
