"""Tier-1 guard: every gossip message type survives its own wire.

Runs scripts/check_gossip_wire.py in-process (the test_env_docs pattern):
every dataclass in gateway/gossip.py MESSAGE_TYPES gets a non-default
probe per declared field, round-tripped through encode_message →
decode_message; version mismatches and unknown fields must refuse. A
field added without wire coverage fails here, not in a mixed fleet.
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_gossip_wire  # noqa: E402

from llmlb_tpu.gateway.gossip import (  # noqa: E402
    MESSAGE_TYPES,
    GossipWireError,
    decode_message,
    encode_message,
)


def test_every_message_type_roundtrips_versioned():
    problems = check_gossip_wire.failures()
    assert not problems, "\n".join(problems)


def test_enumeration_is_not_vacuous():
    """The registry must contain the well-known kinds (no silent pass if
    the MESSAGE_TYPES comprehension breaks)."""
    for kind in ("hello", "tps", "breaker", "rl_spend", "heat", "migrate",
                 "residency"):
        assert kind in MESSAGE_TYPES, kind


@pytest.mark.parametrize("kind", sorted(MESSAGE_TYPES))
def test_per_kind_roundtrip(kind):
    """Per-kind failure granularity on top of the aggregate check."""
    cls = MESSAGE_TYPES[kind]
    assert not check_gossip_wire.check_roundtrip(kind, cls)
    assert not check_gossip_wire.check_rejections(kind, cls)


def test_checker_catches_a_lost_field(monkeypatch):
    """The checker itself must fail when a field does not survive."""

    def lossy_decode(raw):
        k, data, meta = decode_message(raw)
        data.pop(next(iter(sorted(data)), None), None)
        return k, data, meta

    monkeypatch.setattr(check_gossip_wire, "decode_message", lossy_decode)
    kind = "migrate"
    assert check_gossip_wire.check_roundtrip(kind, MESSAGE_TYPES[kind])


def test_decode_rejects_garbage():
    for raw in (b"not json", b"[1,2]", b'{"k": 7}'):
        with pytest.raises(GossipWireError):
            decode_message(raw)


def test_meta_version_is_seq_origin():
    raw = encode_message("tps_clear", {"eid": "e1"}, origin="hostA#w0",
                         seq=9)
    _, _, meta = decode_message(raw)
    assert meta["ver"] == (9, "hostA#w0")
