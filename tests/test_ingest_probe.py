"""Model-ingestion probe (SURVEY §2.3 #2-3 TPU equivalent): checkpoint
validation + StableHLO lowering proof."""

import json
import os
import struct

import numpy as np
import pytest

from llmlb_tpu.tools.ingest_probe import main, probe_checkpoint


def _write_safetensors(path, tensors: dict):
    header = {}
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        data = arr.tobytes()
        dtype = {"float32": "F32", "float16": "F16", "int32": "I32"}[
            str(arr.dtype)]
        header[name] = {"dtype": dtype, "shape": list(arr.shape),
                        "data_offsets": [offset, offset + len(data)]}
        blobs.append(data)
        offset += len(data)
    hjson = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for b in blobs:
            f.write(b)


@pytest.fixture
def good_ckpt(tmp_path):
    d = tmp_path / "ckpt"
    d.mkdir()
    _write_safetensors(str(d / "model.safetensors"), {
        "model.embed_tokens.weight": np.ones((16, 8), np.float32),
        "lm_head.weight": np.ones((16, 8), np.float32),
    })
    (d / "config.json").write_text(json.dumps({
        "vocab_size": 16, "hidden_size": 8, "intermediate_size": 16,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "num_key_value_heads": 2, "max_position_embeddings": 64,
        "rms_norm_eps": 1e-6, "rope_theta": 10000.0,
    }))
    return d


def test_probe_reports_clean_checkpoint(good_ckpt):
    report = probe_checkpoint(str(good_ckpt))
    assert report.tensor_count == 2
    assert report.total_bytes > 0
    assert report.config["num_layers"] == 2
    # layer cross-check only fires when shards carry model.layers.*; a clean
    # header-level pass has no findings
    assert report.ok, report.findings


def test_probe_flags_nan_and_missing_index(tmp_path):
    d = tmp_path / "bad"
    d.mkdir()
    arr = np.ones((8, 8), np.float32)
    arr[3, 3] = np.nan
    _write_safetensors(str(d / "model.safetensors"), {"w": arr})
    (d / "model.safetensors.index.json").write_text(json.dumps({
        "weight_map": {"w": "model.safetensors",
                       "missing.weight": "model-00002.safetensors"}
    }))
    report = probe_checkpoint(str(d))
    joined = " ".join(report.findings)
    assert "non-finite" in joined or "NaN" in joined
    assert "missing from" in joined
    assert not report.ok


def test_probe_empty_dir(tmp_path):
    report = probe_checkpoint(str(tmp_path))
    assert not report.ok
    assert "no .safetensors" in report.findings[0]


def test_probe_cli_and_stablehlo(good_ckpt, tmp_path, capsys):
    out = tmp_path / "prefill.stablehlo"
    rc = main([str(good_ckpt), "--stablehlo", str(out)])
    printed = json.loads(capsys.readouterr().out)
    assert rc == 0, printed
    assert printed["ok"] is True
    assert os.path.getsize(out) > 0
    text = out.read_text()
    assert "stablehlo" in text or "func.func" in text
