"""Tier-1 guard: every scheduler terminal path emits a flight-recorder
event.

Runs scripts/check_lifecycle_events.py in-process: a function in
llmlb_tpu/engine/scheduler.py that puts a terminal ("done"/"error")
event-queue tuple without a matching ``_fr_emit``/``flightrec.emit`` call
fails the build — a missing emit is a silent gap in every merged timeline
(docs/tracing.md).
"""

import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_lifecycle_events  # noqa: E402


def test_scheduler_terminal_paths_instrumented():
    findings = check_lifecycle_events.check_scheduler()
    assert not findings, "\n".join(f"line {ln}: {what}"
                                   for ln, what in findings)


def test_checker_is_not_vacuous():
    """The real scheduler must contain terminal puts the checker pairs —
    a refactor that renames events.put would silently disarm the guard."""
    import ast

    source = check_lifecycle_events.SCHEDULER.read_text()
    tree = ast.parse(source)
    puts = sum(
        1 for n in ast.walk(tree)
        if isinstance(n, ast.Call) and check_lifecycle_events._is_terminal_put(n)
    )
    assert puts >= 10, f"only {puts} terminal puts found — pattern drifted?"


def test_checker_flags_missing_emit(tmp_path):
    bad = tmp_path / "sched.py"
    bad.write_text(textwrap.dedent("""
        class S:
            def _finish(self, request):
                request.events.put(("done", "stop"))

            def _park_slot(self, i):
                pass
    """))
    findings = check_lifecycle_events.check_scheduler(bad)
    assert len(findings) == 2, findings
    assert "terminal events.put" in findings[0][1]
    assert "parked" in findings[1][1]


def test_checker_accepts_instrumented(tmp_path):
    ok = tmp_path / "sched.py"
    ok.write_text(textwrap.dedent("""
        class S:
            def _finish(self, request):
                request.events.put(("done", "stop"))
                self._fr_emit(request, "finished", reason="stop")

            def _fail(self, request):
                request.events.put(("error", "boom"))
                self.flightrec.emit(request.request_id, "errored")

            def _park_slot(self, i):
                self._fr_emit(self.slots[i].request, "parked",
                              reason="preempt")

            def _tokens_only(self, request, tok):
                request.events.put(("token", tok))  # not terminal: no emit
    """))
    assert check_lifecycle_events.check_scheduler(ok) == []
