"""Tier-1 guard: every exported metric name is documented.

Runs scripts/check_metrics_docs.py's cross-check in-process: any series the
engine or gateway registries can emit must appear verbatim in
docs/monitoring/README.md, so new gauges (like the KV page-pool family)
cannot ship undocumented.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_metrics_docs  # noqa: E402


def test_engine_metrics_are_documented():
    docs = check_metrics_docs.DOCS.read_text()
    missing = check_metrics_docs.undocumented(
        check_metrics_docs.engine_metric_names(), docs
    )
    assert not missing, f"undocumented engine metrics: {missing}"


def test_gateway_metrics_are_documented():
    docs = check_metrics_docs.DOCS.read_text()
    missing = check_metrics_docs.undocumented(
        check_metrics_docs.gateway_metric_names(), docs
    )
    assert not missing, f"undocumented gateway metrics: {missing}"


def test_checker_catches_missing_names():
    """The checker itself must fail on an undocumented name (no silent
    vacuous pass if enumeration breaks)."""
    assert check_metrics_docs.undocumented(
        {"llmlb_engine_not_a_real_metric"}, check_metrics_docs.DOCS.read_text()
    ) == ["llmlb_engine_not_a_real_metric"]


def test_dashboard_and_alert_series_exist():
    """Every llmlb_* series referenced by the Grafana dashboard and the
    alert rules must be exportable by some registry — dashboards cannot
    drift from the exporters."""
    referenced = check_metrics_docs.referenced_series(
        check_metrics_docs.GRAFANA, check_metrics_docs.ALERTS
    )
    assert referenced, "asset parsing must find series (not vacuous)"
    dangling = check_metrics_docs.unknown_references(
        referenced, check_metrics_docs.exportable_names()
    )
    assert not dangling, f"dashboard/alert series exported by nothing: {dangling}"


def test_reference_checker_catches_unknown_series():
    """The cross-check itself must flag a made-up series name."""
    assert check_metrics_docs.unknown_references(
        {"llmlb_engine_not_a_real_metric"},
        check_metrics_docs.exportable_names(),
    ) == ["llmlb_engine_not_a_real_metric"]
