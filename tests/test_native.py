"""C++ native components vs their Python twins on identical inputs."""

import hashlib
import json

import numpy as np
import pytest

from llmlb_tpu.native import (
    NativeSafetensors,
    NativeSseScanner,
    load_native,
    native_chain_hash,
)

from tests.conftest import native_skip_reason

pytestmark = pytest.mark.skipif(
    load_native() is None,
    reason=native_skip_reason() or "native library unavailable",
)


def test_chain_hash_matches_hashlib():
    prev = "0" * 64
    entries = [b'["a",1]', b'["b",2]', "unicode-é".encode()]
    expected = hashlib.sha256(prev.encode() + b"".join(entries)).hexdigest()
    assert native_chain_hash(prev, entries) == expected
    # empty batch, long entries, 1-byte entries
    assert native_chain_hash(prev, []) == hashlib.sha256(prev.encode()).hexdigest()
    big = [b"x" * 100_000, b"y"]
    assert native_chain_hash(prev, big) == hashlib.sha256(
        prev.encode() + b"".join(big)).hexdigest()


def test_audit_batch_hash_uses_native_consistently():
    """audit.batch_hash must produce the same digest whether or not the
    native library is loaded (the chain must survive a build change)."""
    import time

    from llmlb_tpu.gateway import audit as audit_mod

    entries = [
        audit_mod.AuditEntry(ts=time.time(), method="GET", path="/x",
                             status=200, duration_ms=1.0)
        for _ in range(5)
    ]
    native_digest = audit_mod.batch_hash("0" * 64, entries)
    h = hashlib.sha256()
    h.update(("0" * 64).encode())
    for e in entries:
        h.update(e.canonical().encode())
    assert native_digest == h.hexdigest()


def test_safetensors_reader_matches_safetensors_package(tmp_path):
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    tensors = {
        "model.layers.0.w": rng.standard_normal((16, 8)).astype(np.float32),
        "bias": rng.standard_normal((8,)).astype(np.float16),
        "ids": np.arange(10, dtype=np.int64),
        "scalarish": np.ones((1,), np.float32),
    }
    path = str(tmp_path / "m.safetensors")
    save_file(tensors, path, metadata={"format": "pt"})

    reader = NativeSafetensors(path)
    assert sorted(reader.keys()) == sorted(tensors)
    for name, ref in tensors.items():
        got = reader.get_tensor(name)
        assert got.dtype == ref.dtype and got.shape == ref.shape
        np.testing.assert_array_equal(np.array(got), ref)
    reader.close()


def test_safetensors_reader_bf16(tmp_path):
    import ml_dtypes
    from safetensors.numpy import save_file

    arr = np.arange(24, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(4, 6)
    path = str(tmp_path / "bf16.safetensors")
    save_file({"w": arr}, path)
    reader = NativeSafetensors(path)
    got = np.array(reader.get_tensor("w"))
    np.testing.assert_array_equal(got, arr)


def test_safetensors_reader_rejects_garbage(tmp_path):
    path = str(tmp_path / "bad.safetensors")
    with open(path, "wb") as f:
        f.write(b"\xff" * 64)
    with pytest.raises(ValueError):
        NativeSafetensors(path)
    with pytest.raises(ValueError):
        NativeSafetensors(str(tmp_path / "missing.safetensors"))


def test_ct_equal_matches_compare_digest():
    import hmac

    from llmlb_tpu.native import native_ct_equal

    cases = [
        (b"", b""), (b"a", b"a"), (b"a", b"b"), (b"a", b""),
        (b"sk_" + b"x" * 43, b"sk_" + b"x" * 43),
        (b"sk_" + b"x" * 43, b"sk_" + b"x" * 42 + b"y"),
        (b"\x00\x01\x02", b"\x00\x01\x02"),  # embedded NULs must compare
        (b"\x00\x01\x02", b"\x00\x01\x03"),
        (b"abc", b"abcd"), (b"abcd", b"abc"),
    ]
    for a, b in cases:
        got = native_ct_equal(a, b)
        assert got is not None
        assert got == hmac.compare_digest(a, b), (a, b)


def _sse_frames(payloads):
    return b"".join(
        b"data: " + json.dumps(p).encode() + b"\n\n" for p in payloads
    ) + b"data: [DONE]\n\n"


def test_sse_scanner_matches_python_accumulator():
    from llmlb_tpu.gateway.token_accounting import StreamingTokenAccumulator

    stream = _sse_frames([
        {"choices": [{"delta": {"content": "hel"}}]},
        {"choices": [{"delta": {"content": "lo"}}]},
        {"choices": [], "usage": {"prompt_tokens": 11, "completion_tokens": 2}},
    ])
    scanner = NativeSseScanner()
    # ragged feeding: split at awkward boundaries
    for i in range(0, len(stream), 7):
        scanner.feed(stream[i:i + 7])
    assert scanner.frames == 3
    assert scanner.usage() == (11, 2)

    acc = StreamingTokenAccumulator()
    for i in range(0, len(stream), 7):
        acc.feed(stream[i:i + 7])
    assert acc.finalize() == (11, 2, True)


def test_sse_scanner_fuzz_parity_random_chunk_boundaries():
    """Parity fuzz (guards the native fast path against drift): identical
    byte streams, split at randomized chunk boundaries, through the native
    scanner and the pure-Python splitter must report identical frame counts
    and usage. Streams mix normal deltas, usage placement variants,
    [DONE], comments, CRLF, partial junk, and multi-frame chunks."""
    import random

    from llmlb_tpu.gateway.token_accounting import StreamingTokenAccumulator

    rng = random.Random(0xC0FFEE)

    def random_stream() -> bytes:
        frames = []
        n = rng.randrange(1, 12)
        for i in range(n):
            roll = rng.random()
            if roll < 0.5:
                frames.append(
                    {"choices": [{"delta": {"content": f"tok{i}" * rng.randrange(1, 4)}}]}
                )
            elif roll < 0.65:
                frames.append({"choices": [],
                               "usage": {"prompt_tokens": rng.randrange(0, 500),
                                         "completion_tokens": rng.randrange(0, 500)}})
            elif roll < 0.75:
                frames.append({"type": "response.output_text.delta",
                               "delta": "x" * rng.randrange(1, 30)})
            elif roll < 0.85:
                frames.append({"choices": [{"delta": {}}],
                               "usage": {"input_tokens": rng.randrange(0, 99),
                                         "output_tokens": rng.randrange(0, 99)}})
            else:
                frames.append({"choices": [{"delta": {"content": 'q"u\\o✓te'}}]})
        raw = b""
        for f in frames:
            body = json.dumps(f).encode()
            sep = rng.choice([b"\n\n", b"\r\n\r\n", b"\n"])
            prefix = rng.choice([b"data: ", b"data:", b"data:  "])
            raw += prefix + body + sep
            if rng.random() < 0.2:
                raw += rng.choice([b": keepalive\n", b"event: ping\n",
                                   b"\n", b"data:\n"])
        if rng.random() < 0.8:
            raw += b"data: [DONE]\n\n"
        return raw

    for case in range(50):
        stream = random_stream()
        # random chunking: 1..23-byte slices, including empty-chunk no-ops
        chunks = []
        pos = 0
        while pos < len(stream):
            step = rng.randrange(1, 24)
            chunks.append(stream[pos:pos + step])
            pos += step

        scanner = NativeSseScanner()
        acc = StreamingTokenAccumulator()
        acc._native = None  # force the pure-Python splitter
        acc._raw = None
        for c in chunks:
            scanner.feed(c)
            acc._feed_python(c)
        assert scanner.frames == acc._chunks_seen, (
            f"case {case}: frame count diverged "
            f"(native {scanner.frames} vs python {acc._chunks_seen})\n"
            f"stream={stream!r}"
        )
        native_usage = scanner.usage()
        python_usage = acc._usage
        if python_usage is not None and python_usage != (0, 0):
            assert native_usage == python_usage, (
                f"case {case}: usage diverged "
                f"(native {native_usage} vs python {python_usage})\n"
                f"stream={stream!r}"
            )


def test_sse_scanner_responses_api_usage_and_no_usage():
    scanner = NativeSseScanner()
    scanner.feed(_sse_frames([
        {"type": "response.output_text.delta", "delta": "x"},
        {"type": "response.completed",
         "response": {}, "usage": {"input_tokens": 4, "output_tokens": 9}},
    ]))
    assert scanner.usage() == (4, 9)

    empty = NativeSseScanner()
    empty.feed(_sse_frames([{"choices": [{"delta": {"content": "x"}}]}]))
    assert empty.usage() is None
