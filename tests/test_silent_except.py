"""Tier-1 guard: no silent exception swallows in llmlb_tpu/.

Runs scripts/check_silent_except.py in-process: bare ``except:`` and
``except Exception: pass`` handlers without an explicit
``# allow-silent: <reason>`` annotation fail the build — crash-recovery
code (durable streams, drain, failover) must not hide the errors it
exists to surface.
"""

import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "scripts"))

import check_silent_except  # noqa: E402


def test_no_silent_swallows_in_tree():
    findings = []
    for path in sorted(check_silent_except.SRC.rglob("*.py")):
        for lineno, what in check_silent_except.check_file(path):
            findings.append(f"{path.relative_to(check_silent_except.REPO)}:"
                            f"{lineno}: {what}")
    assert not findings, "\n".join(findings)


def test_checker_flags_the_patterns(tmp_path):
    """The checker must catch both flagged shapes and honor the marker."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        try:
            x = 1
        except:
            x = 2
        try:
            y = 1
        except Exception:
            pass
    """))
    findings = check_silent_except.check_file(bad)
    assert len(findings) == 2
    assert findings[0][1] == "bare `except:`"

    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""
        import logging
        try:
            x = 1
        except Exception:
            logging.exception("boom")  # surfaced: not a swallow
        try:
            y = 1
        except Exception:  # allow-silent: unit-test fixture teardown
            pass
        try:
            z = 1
        except ValueError:
            pass  # narrow excepts may pass silently — they chose a type
    """))
    assert check_silent_except.check_file(ok) == []
